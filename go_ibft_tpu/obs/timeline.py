"""Cross-node consensus timeline reconstruction.

Merges N per-node flight-recorder exports (or one multi-track loopback
export) into a per-height consensus timeline:

    proposal broadcast -> PREPARE quorum waterfall -> COMMIT quorum ->
    finalize

and computes the height's **critical path**: which node finalized last,
which validator's message completed each quorum there, and how the time
split between network wait, signature verification, and drain wakeup.

Inputs are the Chrome ``trace_event`` documents ``obs/export.py`` writes.
The records that matter:

* ``net.send`` instants (args: height, round, type, span) — stamped by
  the engine at multicast time on the sender's track;
* ``net.recv`` instants (args: origin, height, round, type, span,
  sent_us) — recorded at delivery on the receiver's track (engine ingress
  for loopback dispatch, the wire boundary for ``GrpcTransport``);
* ``sequence.start`` / ``sequence.done`` instants (args: height) — the
  per-node height window;
* ``verify.drain`` / ``*.drain`` spans — verification and phase-drain
  time attribution on the node's track.

Cross-process clock alignment uses each file's ``otherData.clockOffsetsUs``
(the :mod:`go_ibft_tpu.obs.clock` min one-way-delay estimates): events
from a foreign file are rebased onto the reference file's clock via the
reference node's estimate for that origin.  The estimates are upper
bounds (true offset + min one-way delay), so sub-millisecond cross-node
orderings are approximate — the per-node quorum waterfalls, which only
ever compare timestamps recorded on ONE clock, are exact.  Loopback
exports share one clock and skip alignment entirely.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "TraceFile",
    "Event",
    "load_trace_file",
    "merge_events",
    "default_quorum",
    "reconstruct",
    "HeightTimeline",
    "NodePhases",
    "render_report",
    "to_perfetto",
    "merge_device_trace",
]

# Message-type codes as stamped in net.send/net.recv args (wire enum).
_PREPREPARE, _PREPARE, _COMMIT = 0, 1, 2

# Span names counted as signature-verification work on a node's track.
_VERIFY_SPANS = frozenset({"verify.drain"})
# Span names counted as phase-drain (store walk + state machine) work.
_DRAIN_SPANS = frozenset({"proposal.drain", "prepare.drain", "commit.drain"})


@dataclass
class Event:
    """One normalized record on the merged timeline (µs, aligned clock)."""

    name: str
    track: str
    ts: int
    dur: int
    args: dict
    ph: str
    source: str  # originating trace file (diagnostics)


@dataclass
class TraceFile:
    """One parsed export: events with resolved track names + metadata."""

    path: str
    node: Optional[str]
    clock_offsets: Dict[str, int]
    dropped: int
    events: List[Event]


def load_trace_file(path: str) -> TraceFile:
    """Parse one ``obs/export.py`` document into normalized events."""
    with open(path) as fh:
        doc = json.load(fh)
    other = doc.get("otherData", {}) or {}
    offsets_raw = other.get("clockOffsetsUs", {}) or {}
    clock_offsets = {
        origin: int(entry.get("offset_us", 0))
        if isinstance(entry, dict)
        else int(entry)
        for origin, entry in offsets_raw.items()
    }
    tracks: Dict[int, str] = {}
    events: List[Event] = []
    for e in doc.get("traceEvents", []):
        if e.get("ph") == "M":
            if e.get("name") == "thread_name":
                tracks[e["tid"]] = e.get("args", {}).get("name", str(e["tid"]))
            continue
        events.append(
            Event(
                name=e.get("name", ""),
                track=tracks.get(e.get("tid"), str(e.get("tid"))),
                ts=int(e.get("ts", 0)),
                dur=int(e.get("dur", 0)),
                args=e.get("args", {}) or {},
                ph=e.get("ph", "i"),
                source=path,
            )
        )
    return TraceFile(
        path=path,
        node=other.get("node"),
        clock_offsets=clock_offsets,
        dropped=int(other.get("droppedRecords", 0) or 0),
        events=events,
    )


def merge_events(traces: Sequence[TraceFile]) -> List[Event]:
    """Concatenate per-file events on one clock (reference = file 0).

    For a foreign file whose ``node`` identity the reference file holds a
    clock-offset estimate for, every event timestamp is rebased with that
    estimate; files without an estimate (loopback, or no traffic yet)
    merge unshifted.  Note each export already rebased its own timestamps
    to its earliest record, so the ``net.recv.sent_us`` args — NOT the
    event ``ts`` fields — carry the raw cross-file clock relation; the
    offset estimates come from the same raw pairs, so the rebase below
    first undoes each file's export rebase using the raw anchor stored in
    its own send/recv args.
    """
    if not traces:
        return []
    merged: List[Event] = []
    reference = traces[0]
    for trace_file in traces:
        shift = 0
        if trace_file is not reference and trace_file.node is not None:
            # Raw-clock anchors: export rebased ts to the file's earliest
            # raw timestamp; net.send instants carry no raw copy, but
            # net.recv ones carry the ORIGIN's raw sent_us.  Recover each
            # file's rebase base from any (event ts, raw ts) pair it has.
            est = reference.clock_offsets.get(trace_file.node)
            ref_base = _rebase_anchor(reference)
            own_base = _rebase_anchor(trace_file)
            if est is not None and ref_base is not None and own_base is not None:
                # foreign raw = ts + own_base; local raw ~= foreign raw +
                # est; local ts = local raw - ref_base.
                shift = own_base + est - ref_base
        for event in trace_file.events:
            if shift:
                event = Event(
                    event.name,
                    event.track,
                    event.ts + shift,
                    event.dur,
                    event.args,
                    event.ph,
                    event.source,
                )
            merged.append(event)
    merged.sort(key=lambda e: e.ts)
    return merged


def _rebase_anchor(trace_file: TraceFile) -> Optional[int]:
    """The export's rebase base: raw_ts - exported_ts for this file.

    A node's OWN ``net.send`` and the self-delivered ``net.recv`` carry
    ``sent_us`` (raw clock) in args while ``ts`` is export-rebased; their
    difference recovers the base.  Falls back to ``None`` when the file
    recorded no traced sends (alignment then degrades to no shift).
    """
    for event in trace_file.events:
        if event.name == "net.recv" and event.args.get("origin") == trace_file.node:
            sent = event.args.get("sent_us")
            span_ts = _send_ts(trace_file, event.args.get("span"))
            if sent is not None and span_ts is not None:
                return int(sent) - span_ts
    return None


def _send_ts(trace_file: TraceFile, span_id) -> Optional[int]:
    if span_id is None:
        return None
    for event in trace_file.events:
        if event.name == "net.send" and event.args.get("span") == span_id:
            return event.ts
    return None


def default_quorum(n: int) -> int:
    """Optimal IBFT quorum for ``n`` equally-weighted validators:
    ``n - floor((n-1)/3)`` (e.g. 3 of 4, 5 of 7, 67 of 100)."""
    return n - (n - 1) // 3


@dataclass
class NodePhases:
    """One node's view of one height (all timestamps µs, merged clock)."""

    node: str
    proposal_recv: Optional[int] = None
    prepare_quorum_at: Optional[int] = None
    prepare_completer: Optional[str] = None
    commit_quorum_at: Optional[int] = None
    commit_completer: Optional[str] = None
    finalized_at: Optional[int] = None
    verify_us: int = 0
    drain_us: int = 0

    def wakeup_us(self) -> Optional[int]:
        """Post-COMMIT-quorum latency not attributable to verify/drain
        spans: event-loop wakeup + store walk scheduling — the drain
        wakeup share of the critical path."""
        if self.finalized_at is None or self.commit_quorum_at is None:
            return None
        tail = self.finalized_at - self.commit_quorum_at
        return max(0, tail - self._busy_after_commit)

    _busy_after_commit: int = 0


@dataclass
class HeightTimeline:
    """The reconstructed consensus timeline for one height."""

    height: int
    proposer: Optional[str]
    proposal_sent: Optional[int]
    nodes: Dict[str, NodePhases] = field(default_factory=dict)

    @property
    def critical_node(self) -> Optional[NodePhases]:
        """The node whose finalize completed the height (the slowest)."""
        finalized = [p for p in self.nodes.values() if p.finalized_at is not None]
        if not finalized:
            return None
        return max(finalized, key=lambda p: p.finalized_at)

    def to_dict(self) -> dict:
        crit = self.critical_node
        return {
            "height": self.height,
            "proposer": self.proposer,
            "proposal_sent_us": self.proposal_sent,
            "critical_node": crit.node if crit else None,
            "critical_path": _phase_split(self, crit) if crit else None,
            "nodes": {
                node: {
                    "proposal_recv_us": p.proposal_recv,
                    "prepare_quorum_us": p.prepare_quorum_at,
                    "prepare_completer": p.prepare_completer,
                    "commit_quorum_us": p.commit_quorum_at,
                    "commit_completer": p.commit_completer,
                    "finalized_us": p.finalized_at,
                    "verify_us": p.verify_us,
                    "drain_us": p.drain_us,
                }
                for node, p in sorted(self.nodes.items())
            },
        }


def _phase_split(tl: HeightTimeline, p: NodePhases) -> dict:
    """The critical node's time split, each leg in µs (None = unknown)."""

    def gap(a, b):
        return (b - a) if (a is not None and b is not None) else None

    return {
        "proposal_broadcast_us": gap(tl.proposal_sent, p.proposal_recv),
        "prepare_wait_us": gap(p.proposal_recv, p.prepare_quorum_at),
        "commit_wait_us": gap(p.prepare_quorum_at, p.commit_quorum_at),
        "finalize_tail_us": gap(p.commit_quorum_at, p.finalized_at),
        "verify_us": p.verify_us,
        "drain_us": p.drain_us,
        "wakeup_us": p.wakeup_us(),
        "total_us": gap(tl.proposal_sent, p.finalized_at),
        "prepare_completer": p.prepare_completer,
        "commit_completer": p.commit_completer,
    }


def reconstruct(
    events: Iterable[Event], *, quorum: Optional[int] = None
) -> List[HeightTimeline]:
    """Merged events -> one :class:`HeightTimeline` per finalized height.

    ``quorum`` defaults to :func:`default_quorum` over the number of
    distinct consensus tracks observed (equal voting powers; pass the
    exact value for weighted sets).
    """
    events = list(events)
    sends: Dict[int, List[Event]] = {}
    recvs: Dict[int, List[Event]] = {}
    seq_done: Dict[Tuple[str, int], int] = {}
    seq_start: Dict[Tuple[str, int], int] = {}
    # Consensus tracks are derived from ENGINE evidence only — outbound
    # net.send instants and sequence boundaries.  net.recv events may
    # additionally land on transport diagnostics tracks (an unnamed
    # GrpcTransport records wire-boundary recvs on ``net-<addr>``); those
    # must neither count as nodes (they would inflate the derived quorum)
    # nor contribute quorum points, so recvs are filtered to consensus
    # tracks below.
    consensus_tracks: set = set()
    busy_by_track: Dict[str, List[Event]] = {}
    for e in events:
        h = e.args.get("height")
        if e.name == "net.send" and h is not None:
            sends.setdefault(h, []).append(e)
            consensus_tracks.add(e.track)
        elif e.name == "net.recv" and h is not None:
            recvs.setdefault(h, []).append(e)
        elif e.name == "sequence.done" and h is not None:
            seq_done[(e.track, h)] = e.ts
            consensus_tracks.add(e.track)
        elif e.name == "sequence.start" and h is not None:
            seq_start[(e.track, h)] = e.ts
            consensus_tracks.add(e.track)
        elif e.ph == "X" and e.name in _VERIFY_SPANS | _DRAIN_SPANS:
            busy_by_track.setdefault(e.track, []).append(e)
    n = len(consensus_tracks)
    k = quorum if quorum is not None else default_quorum(max(1, n))

    heights = sorted(set(sends) | set(recvs))
    out: List[HeightTimeline] = []
    for h in heights:
        h_sends = sends.get(h, [])
        h_recvs = recvs.get(h, [])
        proposals = [e for e in h_sends if e.args.get("type") == _PREPREPARE]
        proposer = min(proposals, key=lambda e: e.ts).track if proposals else None
        proposal_sent = min((e.ts for e in proposals), default=None)
        tl = HeightTimeline(height=h, proposer=proposer, proposal_sent=proposal_sent)

        by_node: Dict[str, List[Event]] = {}
        for e in h_recvs:
            if e.track in consensus_tracks:
                by_node.setdefault(e.track, []).append(e)
        for node in consensus_tracks:
            p = NodePhases(node=node)
            node_recvs = sorted(by_node.get(node, []), key=lambda e: e.ts)
            prop = [e for e in node_recvs if e.args.get("type") == _PREPREPARE]
            if prop:
                p.proposal_recv = prop[0].ts
            elif node == proposer:
                p.proposal_recv = proposal_sent
            p.prepare_quorum_at, p.prepare_completer = _quorum_point(
                node_recvs, _PREPARE, k
            )
            p.commit_quorum_at, p.commit_completer = _quorum_point(
                node_recvs, _COMMIT, k
            )
            p.finalized_at = seq_done.get((node, h))
            # Busy-time attribution inside the node's height window
            # (pre-bucketed by track: a 30-node soak trace must not cost
            # O(nodes x heights x total_events) rescans).
            lo = seq_start.get((node, h), p.proposal_recv)
            hi = p.finalized_at
            if lo is not None and hi is not None:
                for e in busy_by_track.get(node, ()):
                    if e.ts < lo or e.ts > hi:
                        continue
                    if e.name in _VERIFY_SPANS:
                        p.verify_us += e.dur
                    else:
                        p.drain_us += e.dur
                    if (
                        p.commit_quorum_at is not None
                        and e.ts >= p.commit_quorum_at
                    ):
                        p._busy_after_commit += e.dur
            if node_recvs or p.finalized_at is not None or node == proposer:
                tl.nodes[node] = p
        out.append(tl)
    return out


def _quorum_point(
    node_recvs: Sequence[Event], msg_type: int, k: int
) -> Tuple[Optional[int], Optional[str]]:
    """(ts, origin) of the k-th DISTINCT-origin arrival of ``msg_type``.

    First arrival per origin counts (chaos duplication and future-buffer
    re-records are later by construction); for PREPARE the proposer never
    sends one, so its own implicit prepare is not modeled — quorum here
    means k prepare *messages*, matching the engine's message-count
    semantics for equal powers.
    """
    seen: set = set()
    for e in node_recvs:
        if e.args.get("type") != msg_type:
            continue
        origin = e.args.get("origin")
        if origin in seen:
            continue
        seen.add(origin)
        if len(seen) >= k:
            return e.ts, origin
    return None, None


def render_report(timelines: Sequence[HeightTimeline]) -> str:
    """Human-readable per-height critical-path report."""
    lines: List[str] = []
    for tl in timelines:
        crit = tl.critical_node
        lines.append(f"height {tl.height}")
        lines.append(f"  proposer          {tl.proposer or '?'}")
        if crit is None:
            lines.append("  (no node finalized this height in the trace window)")
            continue
        split = _phase_split(tl, crit)

        def ms(v):
            return "?" if v is None else f"{v / 1000:.3f}ms"

        lines.append(
            f"  critical node     {crit.node}  (finalized last, "
            f"total {ms(split['total_us'])})"
        )
        lines.append(
            f"    proposal broadcast {ms(split['proposal_broadcast_us'])}"
        )
        lines.append(
            f"    PREPARE quorum     {ms(split['prepare_wait_us'])}"
            f"  completed by {split['prepare_completer'] or '?'}"
        )
        lines.append(
            f"    COMMIT quorum      {ms(split['commit_wait_us'])}"
            f"  completed by {split['commit_completer'] or '?'}"
        )
        lines.append(
            f"    finalize tail      {ms(split['finalize_tail_us'])}"
            f"  (verify {ms(split['verify_us'])}, drain {ms(split['drain_us'])},"
            f" wakeup {ms(split['wakeup_us'])})"
        )
        waterfall = sorted(
            (p.finalized_at, node)
            for node, p in tl.nodes.items()
            if p.finalized_at is not None
        )
        if waterfall:
            base = waterfall[0][0]
            order = ", ".join(
                f"{node} +{(ts - base) / 1000:.3f}ms" for ts, node in waterfall
            )
            lines.append(f"  finalize waterfall  {order}")
    return "\n".join(lines)


def to_perfetto(traces: Sequence[TraceFile]) -> dict:
    """Merged multi-node Perfetto document: one pid per source file (a
    ``process_name`` row each), tids per track — N single-node exports
    render as N labeled process groups on one aligned clock."""
    events: List[dict] = []
    merged_by_file: Dict[str, List[Event]] = {}
    for event in merge_events(traces):
        merged_by_file.setdefault(event.source, []).append(event)
    dropped = 0
    for pid, trace_file in enumerate(traces):
        dropped += trace_file.dropped
        events.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "name": "process_name",
                "args": {"name": trace_file.node or trace_file.path},
            }
        )
        tids: Dict[str, int] = {}
        for event in merged_by_file.get(trace_file.path, []):
            tid = tids.get(event.track)
            if tid is None:
                tid = tids[event.track] = len(tids)
                events.append(
                    {
                        "ph": "M",
                        "pid": pid,
                        "tid": tid,
                        "name": "thread_name",
                        "args": {"name": event.track},
                    }
                )
            rendered = {
                "ph": event.ph,
                "pid": pid,
                "tid": tid,
                "name": event.name,
                "cat": "obs",
                "ts": event.ts,
                "args": event.args,
            }
            if event.ph == "X":
                rendered["dur"] = event.dur
            elif event.ph == "i":
                rendered["s"] = "t"
            events.append(rendered)
    return {
        "displayTimeUnit": "ms",
        "otherData": {
            "droppedRecords": dropped,
            "sources": [t.path for t in traces],
        },
        "traceEvents": events,
    }


def merge_device_trace(
    doc: dict,
    device_trace_path: str,
    *,
    host_anchor_us: Optional[int] = None,
    keep_python_frames: bool = False,
) -> dict:
    """Merge a ``jax.profiler`` Chrome trace into a host trace document.

    ``doc`` is a flight-recorder export (``obs/export.py``) or a
    :func:`to_perfetto` merge; ``device_trace_path`` is the
    ``*.trace.json.gz`` a :mod:`go_ibft_tpu.obs.devprof` window produced
    (plain ``.json`` accepted too).  The device events land as extra
    process groups (pids above the host ones, each ``process_name``
    prefixed ``device:``) so one Perfetto load shows consensus phases
    over host spans over device ops — the cost-ledger drill-down view.

    Clock alignment: device timestamps are relative to the profiler
    session start; ``host_anchor_us`` (the devprof capture's anchor — the
    flight recorder's monotonic µs clock read at ``start_trace``) minus
    the document's ``otherData.clockBaseUs`` rebases them onto the host
    document's clock.  Without either anchor the device group merges
    unshifted, ordered internally but not aligned (flagged in
    ``otherData.deviceTraceAligned``).

    The profiler's Python-frame events (names starting ``$``) duplicate
    what the flight recorder's spans already show and dominate the file
    size; they are dropped unless ``keep_python_frames``.  Mutates and
    returns ``doc``.
    """
    import gzip

    opener = gzip.open if device_trace_path.endswith(".gz") else open
    with opener(device_trace_path, "rt") as fh:
        device_doc = json.load(fh)

    other = doc.setdefault("otherData", {})
    base = other.get("clockBaseUs")
    shift = 0
    aligned = host_anchor_us is not None and base is not None
    if aligned:
        shift = int(host_anchor_us) - int(base)

    events = doc.setdefault("traceEvents", [])
    pid_base = max((e.get("pid", 0) for e in events), default=0) + 1
    pid_map: Dict[int, int] = {}
    merged = 0
    for e in device_doc.get("traceEvents", []):
        ph = e.get("ph")
        pid = e.get("pid", 0)
        if pid not in pid_map:
            pid_map[pid] = pid_base + len(pid_map)
        out = dict(e)
        out["pid"] = pid_map[pid]
        if ph == "M":
            if e.get("name") == "process_name":
                args = dict(e.get("args", {}))
                args["name"] = f"device:{args.get('name', pid)}"
                out["args"] = args
            events.append(out)
            continue
        if ph != "X":
            continue
        name = e.get("name", "")
        if name.startswith("$") and not keep_python_frames:
            continue
        out["ts"] = e.get("ts", 0) + shift
        out.setdefault("cat", "device")
        events.append(out)
        merged += 1
    other["deviceTrace"] = device_trace_path
    other["deviceTraceAligned"] = aligned
    other["deviceTraceEvents"] = merged
    return doc
