"""Regression gates: fresh evidence vs the best prior round, per backend.

The VERDICT's round-6 mandate as a subsystem: every round produces an
evidence file (``bench.py``'s per-config JSONL), and this module compares
it against the best prior recorded value for the same config **on the
same backend** — so CPU-fallback rounds still catch packing/pipelining/
engine regressions without a chip, and a TPU round is never graded
against a CPU number (or vice versa).

Prior evidence sources, in the repo root:

* ``BENCH_r*.json`` — the driver's per-round artifacts: a JSON object
  whose ``tail`` field holds the run's JSONL lines (plus ``rc``);
* plain ``*.jsonl`` evidence files (``bench_evidence.jsonl``,
  ``evidence_tpu.jsonl``) — one JSON object per line.

Both parse into the same line dicts the bench prints.  Direction
(lower-is-better vs higher-is-better) derives from the metric's unit:
latencies and overhead ratios regress upward, throughputs regress
downward.  Thresholds: > 25% worse than the best prior on the same
backend fails, > 10% warns, anything else passes; configs with no prior
(or no fresh measurement where none was expected) report informationally.
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "GateResult",
    "parse_artifact",
    "artifact_backend",
    "best_prior",
    "gate_evidence",
    "render_table",
    "WARN_PCT",
    "FAIL_PCT",
    "SloSpec",
    "DEFAULT_SLO_TABLE",
    "slo_record",
    "append_slo_records",
    "parse_slo_records",
    "gate_slo_records",
    "ledger_metric_lines",
    "gate_ledger_evidence",
    "LEDGER_WARN_PCT",
    "LEDGER_FAIL_PCT",
]

WARN_PCT = 10.0
FAIL_PCT = 25.0

# Metric keys where HIGHER is better; everything else (ms latencies,
# overhead multipliers) regresses upward.  Units double-check this: any
# per-second unit is a throughput.
_HIGHER_IS_BETTER = ("throughput",)

# Lines that are run diagnostics, not config measurements.
_NON_CONFIG_METRICS = frozenset(
    {
        "bench_platform",
        "bench_error",
        "bench_failures",
        "bench_evidence_gap",
        "backend_probe",
        "tpu_reprobe",
        "adaptive_cutover_calibration",
        "trace_export",
        "cost_ledger",
        "device_trace",
    }
)


def higher_is_better(metric: str, unit: Optional[str]) -> bool:
    if unit and "/s" in unit:
        return True
    return any(tag in metric for tag in _HIGHER_IS_BETTER)


def parse_artifact(path: str) -> List[dict]:
    """Parse one evidence artifact (driver wrapper JSON or raw JSONL)."""
    with open(path) as fh:
        text = fh.read()
    lines: List[dict] = []
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:
        text = doc["tail"]
    elif isinstance(doc, dict):
        return [doc]
    for raw in text.splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            line = json.loads(raw)
        except ValueError:
            continue
        if isinstance(line, dict) and "metric" in line:
            lines.append(line)
    return lines


def artifact_backend(lines: Iterable[dict]) -> str:
    """``tpu`` or ``cpu-fallback`` for a parsed artifact.

    New-schema lines carry an explicit ``backend`` field; older rounds are
    inferred from their ``bench_platform`` line (absence of one — or a
    CPU/fallback platform — means no TPU evidence).
    """
    lines = list(lines)
    for line in lines:
        backend = line.get("backend")
        if backend in ("tpu", "cpu-fallback"):
            return backend
    for line in lines:
        if line.get("metric") == "bench_platform":
            platform = str(line.get("value", ""))
            return "tpu" if platform in ("tpu", "axon") else "cpu-fallback"
    return "cpu-fallback"


def config_lines(lines: Iterable[dict]) -> Dict[str, dict]:
    """metric-key -> line for the measurement lines of one artifact."""
    out: Dict[str, dict] = {}
    for line in lines:
        metric = line.get("metric")
        if metric in _NON_CONFIG_METRICS or metric is None:
            continue
        # Last line per key wins (a re-run within one artifact supersedes).
        if isinstance(line.get("value"), (int, float)):
            out[metric] = line
        else:
            out.setdefault(metric, line)
    return out


def _round_of(path: str) -> int:
    m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def best_prior(
    repo_dir: str, backend: str, exclude: Tuple[str, ...] = ()
) -> Dict[str, Tuple[float, str, dict]]:
    """Best prior value per config on ``backend`` across ``BENCH_r*.json``.

    Returns ``{config: (value, source_name, line)}`` where *best* is
    direction-aware (lowest latency / highest throughput recorded by any
    prior round on the same backend).
    """
    best: Dict[str, Tuple[float, str, dict]] = {}
    paths = sorted(glob.glob(os.path.join(repo_dir, "BENCH_r*.json")), key=_round_of)
    for path in paths:
        name = os.path.basename(path)
        if name in exclude:
            continue
        try:
            lines = parse_artifact(path)
        except OSError:
            continue
        if artifact_backend(lines) != backend:
            continue
        for metric, line in config_lines(lines).items():
            value = line.get("value")
            if not isinstance(value, (int, float)):
                continue
            prior = best.get(metric)
            better = higher_is_better(metric, line.get("unit"))
            if (
                prior is None
                or (better and value > prior[0])
                or (not better and value < prior[0])
            ):
                best[metric] = (float(value), name, line)
    return best


@dataclass
class GateResult:
    config: str
    backend: str
    status: str  # "pass" | "warn" | "fail" | "info"
    fresh: Optional[float]
    prior: Optional[float]
    prior_source: str
    change_pct: Optional[float]  # signed; positive = regression
    note: str = ""


def gate_evidence(
    fresh_lines: Iterable[dict],
    repo_dir: str = ".",
    *,
    backend: Optional[str] = None,
    warn_pct: float = WARN_PCT,
    fail_pct: float = FAIL_PCT,
    exclude: Tuple[str, ...] = (),
) -> List[GateResult]:
    """Compare a fresh evidence artifact against the best prior rounds.

    ``exclude`` names ``BENCH_r*.json`` basenames to drop from the prior
    pool (a fresh artifact that IS one of them must not compare against
    itself).
    """
    fresh_lines = list(fresh_lines)
    if backend is None:
        backend = artifact_backend(fresh_lines)
    fresh = config_lines(fresh_lines)
    prior = best_prior(repo_dir, backend, exclude=exclude)
    results: List[GateResult] = []
    for config in sorted(set(fresh) | set(prior)):
        fresh_line = fresh.get(config)
        fresh_value = fresh_line.get("value") if fresh_line else None
        prior_hit = prior.get(config)
        if prior_hit is None:
            results.append(
                GateResult(
                    config,
                    backend,
                    "info",
                    fresh_value,
                    None,
                    "-",
                    None,
                    note="no prior evidence on this backend (first measurement)",
                )
            )
            continue
        prior_value, source, prior_line = prior_hit
        if not isinstance(fresh_value, (int, float)):
            results.append(
                GateResult(
                    config,
                    backend,
                    "warn",
                    None,
                    prior_value,
                    source,
                    None,
                    note=str(
                        (fresh_line or {}).get("note")
                        or (fresh_line or {}).get("error")
                        or "config produced no measurement this run"
                    )[:80],
                )
            )
            continue
        better = higher_is_better(config, prior_line.get("unit"))
        if prior_value == 0:
            change = 0.0
        elif better:
            change = (prior_value - fresh_value) / abs(prior_value) * 100.0
        else:
            change = (fresh_value - prior_value) / abs(prior_value) * 100.0
        if change > fail_pct:
            status = "fail"
        elif change > warn_pct:
            status = "warn"
        else:
            status = "pass"
        results.append(
            GateResult(
                config,
                backend,
                status,
                float(fresh_value),
                prior_value,
                source,
                round(change, 1),
            )
        )
    return results


def render_table(results: List[GateResult]) -> str:
    """Fixed-width pass/warn/fail table for terminals and CI logs."""
    headers = ("config", "backend", "fresh", "best prior", "source", "Δ%", "status")
    rows = [headers]
    for r in results:
        rows.append(
            (
                r.config,
                r.backend,
                "-" if r.fresh is None else f"{r.fresh:g}",
                "-" if r.prior is None else f"{r.prior:g}",
                r.prior_source,
                "-" if r.change_pct is None else f"{r.change_pct:+.1f}",
                r.status.upper() + (f"  ({r.note})" if r.note else ""),
            )
        )
    widths = [
        max(len(row[i]) for row in rows) for i in range(len(headers) - 1)
    ]
    out = []
    for i, row in enumerate(rows):
        line = "  ".join(
            cell.ljust(widths[j]) for j, cell in enumerate(row[:-1])
        )
        out.append(line + "  " + row[-1])
        if i == 0:
            out.append("-" * len(out[0]))
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Cost-ledger gates (ISSUE 14): per-config dispatch-count + occupancy
# ---------------------------------------------------------------------------
#
# Every evidence line carries a ``ledger`` block (the delta the config
# cost — see ``obs/evidence.py::EvidenceWriter._ledger_block``).  These
# gates grade the two values that regress silently: dispatch count (a
# batching regression shows up as MORE launches for the same work — the
# thresholds are tight because counts are near-deterministic per config)
# and live-lane occupancy (bucket-padding waste creeping up as buckets
# drift away from real lane counts).

LEDGER_WARN_PCT = 5.0
LEDGER_FAIL_PCT = 30.0


def ledger_metric_lines(lines: Iterable[dict]) -> List[dict]:
    """Synthesize gateable metric lines from evidence-line sub-fields.

    ``<config>.ledger_dispatches`` (lower is better) and
    ``<config>.ledger_occupancy`` (higher is better — the ``/s``-free
    unit is special-cased in :func:`gate_ledger_evidence`) from ledger
    blocks, plus ``<config>.boot_cold_ms`` / ``<config>.boot_cached_ms``
    (both lower-better walls) from the boot warm-start config's evidence
    line — a cached-boot regression fails CI exactly like a throughput
    regression.  Lines without these fields (pre-ISSUE-14/16 artifacts)
    yield nothing, so old rounds grade ``info``.
    """
    out: List[dict] = []
    for line in lines:
        metric = line.get("metric")
        if metric is None or metric in _NON_CONFIG_METRICS:
            continue
        block = line.get("ledger")
        if isinstance(block, dict):
            dispatches = block.get("dispatches")
            if isinstance(dispatches, (int, float)) and dispatches > 0:
                out.append(
                    {
                        "metric": f"{metric}.ledger_dispatches",
                        "value": dispatches,
                        "unit": "dispatches",
                        "backend": line.get("backend"),
                    }
                )
                occupancy = block.get("occupancy")
                if isinstance(occupancy, (int, float)):
                    out.append(
                        {
                            "metric": f"{metric}.ledger_occupancy",
                            "value": occupancy,
                            "unit": "fraction",
                            "backend": line.get("backend"),
                        }
                    )
        for field in ("boot_cold_ms", "boot_cached_ms"):
            value = line.get(field)
            if isinstance(value, (int, float)) and value > 0:
                out.append(
                    {
                        "metric": f"{metric}.{field}",
                        "value": value,
                        "unit": "ms",
                        "backend": line.get("backend"),
                    }
                )
    return out


def _ledger_higher_is_better(metric: str) -> bool:
    return metric.endswith(".ledger_occupancy")


def gate_ledger_evidence(
    fresh_lines: Iterable[dict],
    repo_dir: str = ".",
    *,
    backend: Optional[str] = None,
    warn_pct: float = LEDGER_WARN_PCT,
    fail_pct: float = LEDGER_FAIL_PCT,
    exclude: Tuple[str, ...] = (),
) -> List[GateResult]:
    """Grade fresh ledger blocks against the best prior round, same
    backend (the :func:`gate_evidence` posture applied to the synthetic
    ledger metrics).  Configs whose priors carry no ledger block report
    ``info`` — the gate arms itself as rounds accumulate."""
    fresh_lines = list(fresh_lines)
    if backend is None:
        backend = artifact_backend(fresh_lines)
    fresh = {
        line["metric"]: line for line in ledger_metric_lines(fresh_lines)
    }
    prior: Dict[str, Tuple[float, str]] = {}
    paths = sorted(
        glob.glob(os.path.join(repo_dir, "BENCH_r*.json")), key=_round_of
    )
    for path in paths:
        name = os.path.basename(path)
        if name in exclude:
            continue
        try:
            lines = parse_artifact(path)
        except OSError:
            continue
        if artifact_backend(lines) != backend:
            continue
        for synth in ledger_metric_lines(lines):
            metric, value = synth["metric"], float(synth["value"])
            hit = prior.get(metric)
            better = _ledger_higher_is_better(metric)
            if (
                hit is None
                or (better and value > hit[0])
                or (not better and value < hit[0])
            ):
                prior[metric] = (value, name)
    results: List[GateResult] = []
    for metric in sorted(set(fresh) | set(prior)):
        fresh_line = fresh.get(metric)
        fresh_value = fresh_line.get("value") if fresh_line else None
        hit = prior.get(metric)
        if hit is None or not isinstance(fresh_value, (int, float)):
            results.append(
                GateResult(
                    metric,
                    backend,
                    "info",
                    fresh_value,
                    hit[0] if hit else None,
                    hit[1] if hit else "-",
                    None,
                    note=(
                        "no prior ledger evidence on this backend"
                        if hit is None
                        else "config carried no ledger block this run"
                    ),
                )
            )
            continue
        prior_value, source = hit
        better = _ledger_higher_is_better(metric)
        if prior_value == 0:
            change = 0.0
        elif better:
            change = (prior_value - fresh_value) / abs(prior_value) * 100.0
        else:
            change = (fresh_value - prior_value) / abs(prior_value) * 100.0
        if change > fail_pct:
            status = "fail"
        elif change > warn_pct:
            status = "warn"
        else:
            status = "pass"
        results.append(
            GateResult(
                metric,
                backend,
                status,
                float(fresh_value),
                prior_value,
                source,
                round(change, 1),
            )
        )
    return results


# ---------------------------------------------------------------------------
# SLO soak gates (cross-process telemetry plane)
# ---------------------------------------------------------------------------
#
# The bench gates above compare a fresh measurement against the best PRIOR
# measurement; soak SLOs are absolute contracts instead — liveness either
# held or it did not, regardless of history.  The soaks (chain soak, chaos
# matrix, timeline smoke) emit one JSONL record per SLO::
#
#     {"slo": "missed_heights", "value": 0, "unit": "heights",
#      "warn": 0, "fail": 0, "context": {"nodes": 30, "heights": 20}}
#
# and this gate grades each record direction-aware against its limits
# (per-record limits win; DEFAULT_SLO_TABLE supplies the standing ones).
# ``value > fail`` (or ``< fail`` for higher-is-better SLOs) fails the
# run the same way a perf regression does — CI treats both alike.


@dataclass
class SloSpec:
    """Standing limits for one SLO family."""

    warn: Optional[float]
    fail: Optional[float]
    higher_is_better: bool = False
    unit: str = ""


DEFAULT_SLO_TABLE: Dict[str, SloSpec] = {
    # Liveness: ANY missed height is a failure — the cross-process
    # missed_heights=0 posture of bench config #12's QoS gate, applied to
    # every soak.
    "missed_heights": SloSpec(warn=0, fail=0, unit="heights"),
    # Safety proxy: divergent per-node chains (should be impossible; the
    # soaks also assert it directly, but the record makes CI evidence).
    "diverged_chains": SloSpec(warn=0, fail=0, unit="nodes"),
    # Latency: per-height finalize tail under chaos.  The standing limits
    # are deliberately loose (CI hosts vary wildly); individual soaks
    # pass tighter per-record limits scaled to their round timeout.
    "finalize_p99_ms": SloSpec(warn=10_000.0, fail=30_000.0, unit="ms"),
    # Degradation budgets: shed verify work and quarantined lanes are
    # legitimate under injected faults but a sudden flood of either is a
    # regression in disguise.
    "shed_lanes": SloSpec(warn=0, fail=None, unit="lanes"),
    "quarantined_lanes": SloSpec(warn=0, fail=None, unit="lanes"),
    # Sync should only ever repair stranded tails, never carry the soak.
    "sync_fraction": SloSpec(warn=0.25, fail=0.5, unit="fraction"),
    # Byzantine invariants (sim/invariants.py): a single violation of
    # agreement, validity, or post-GST bounded-rounds liveness fails the
    # run — these are the properties the f<N/3 argument promises, and a
    # violating seed is replayable from its CHAOS-REPLAY line.
    "invariant_agreement": SloSpec(warn=0, fail=0, unit="violations"),
    "invariant_validity": SloSpec(warn=0, fail=0, unit="violations"),
    "invariant_bounded_rounds": SloSpec(warn=0, fail=0, unit="violations"),
    # Clean/degraded heights-per-second ratio of the Byzantine soak
    # (bench config #16).  The expensive part is deterministic per seed
    # — round-timeout penalties where an adversary holds round 0 — so
    # the limits bound the seeded attack cost plus host noise, not a
    # tight perf promise.  Lower is better (unit has no "/s"), so a
    # regression is the ratio drifting UP.
    "byzantine_soak_overhead_x": SloSpec(warn=25.0, fail=200.0, unit="x"),
    # Multi-process fleet (bench config #17 / scripts/fleet.py): N real
    # validator processes over TCP under a concurrent proof-client
    # flood.  Chain divergence across processes and an uncut slowloris
    # socket are zero-tolerance; the proof-latency tail is bounded
    # loosely (1-core CI hosts serve hundreds of concurrent clients) and
    # tightened per-run by the harness flags.
    "fleet_diverged_chains": SloSpec(warn=0, fail=0, unit="nodes"),
    "fleet_slowloris_uncut": SloSpec(warn=0, fail=0, unit="sockets"),
    "fleet_proof_p99_ms": SloSpec(warn=10_000.0, fail=30_000.0, unit="ms"),
}


def slo_record(
    name: str,
    value: float,
    *,
    warn: Optional[float] = None,
    fail: Optional[float] = None,
    unit: Optional[str] = None,
    context: Optional[dict] = None,
) -> dict:
    """Build one SLO record (explicit limits override the table's)."""
    spec = DEFAULT_SLO_TABLE.get(name)
    record = {
        "slo": name,
        "value": value,
        "warn": warn if warn is not None else (spec.warn if spec else None),
        "fail": fail if fail is not None else (spec.fail if spec else None),
        "unit": unit if unit is not None else (spec.unit if spec else ""),
    }
    if context:
        record["context"] = context
    return record


def append_slo_records(path: Optional[str], records: Iterable[dict]) -> None:
    """Append records as JSONL (no-op on ``None`` path — soaks call this
    unconditionally and the env var decides whether evidence lands)."""
    if not path:
        return
    with open(path, "a") as fh:
        for record in records:
            fh.write(json.dumps(record) + "\n")


def parse_slo_records(path: str) -> List[dict]:
    """Parse one SLO JSONL file (lines without a ``slo`` key are skipped)."""
    records: List[dict] = []
    with open(path) as fh:
        for raw in fh:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                line = json.loads(raw)
            except ValueError:
                continue
            if isinstance(line, dict) and "slo" in line:
                records.append(line)
    return records


def gate_slo_records(
    records: Iterable[dict],
    table: Optional[Dict[str, SloSpec]] = None,
) -> List[GateResult]:
    """Grade SLO records pass/warn/fail against absolute limits.

    Reuses :class:`GateResult` (and therefore :func:`render_table`):
    ``prior`` holds the fail limit, ``change_pct`` the headroom consumed.
    Records naming an SLO with no limits anywhere report ``info``.
    """
    table = DEFAULT_SLO_TABLE if table is None else table
    results: List[GateResult] = []
    for record in records:
        name = str(record.get("slo"))
        value = record.get("value")
        spec = table.get(name)
        higher = spec.higher_is_better if spec else False
        warn = record.get("warn", spec.warn if spec else None)
        fail = record.get("fail", spec.fail if spec else None)
        note = ""
        context = record.get("context")
        if context:
            note = str(context)[:60]
        if not isinstance(value, (int, float)):
            results.append(
                GateResult(
                    name, "slo", "warn", None, fail, "slo-limit", None,
                    note="record carries no numeric value",
                )
            )
            continue
        value = float(value)

        def breached(limit: Optional[float]) -> bool:
            if limit is None:
                return False
            return value < limit if higher else value > limit

        if breached(fail):
            status = "fail"
        elif breached(warn):
            status = "warn"
        elif warn is None and fail is None:
            status = "info"
        else:
            status = "pass"
        headroom = None
        if fail not in (None, 0):
            headroom = round(value / fail * 100.0, 1)
        results.append(
            GateResult(
                name,
                "slo",
                status,
                value,
                fail,
                "slo-limit",
                headroom,
                note=note,
            )
        )
    return results
