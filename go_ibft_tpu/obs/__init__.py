"""Observability subsystem: flight recorder, hang-proof evidence, gates.

Three pillars (ISSUE 4):

* :mod:`~go_ibft_tpu.obs.trace` / :mod:`~go_ibft_tpu.obs.recorder` — a
  zero-dependency, thread-safe span API recording into a fixed-size ring
  buffer, instrumented at every hot seam (engine round phases, verify
  pack/dispatch/device-wait, transport sends/retries, chaos injection
  sites).  Disabled mode costs a single predicate check per call site.
* :mod:`~go_ibft_tpu.obs.export` — Chrome ``trace_event`` / Perfetto JSON
  export, so a multi-node height renders as a readable multi-track
  timeline (``bench.py --trace out.json``, ``scripts/chaos_replay.py
  --trace``).
* :mod:`~go_ibft_tpu.obs.evidence` — hang-proof evidence capture: device
  probing in a subprocess with a hard wall-clock deadline and a cached
  backend fingerprint (TTL + ``--reprobe``), plus an append-only,
  per-record-flushed JSONL evidence writer so every bench config leaves a
  record even when the run crashes mid-way.  Supersedes
  ``go_ibft_tpu.bench.evidence``.
* :mod:`~go_ibft_tpu.obs.ledger` / :mod:`~go_ibft_tpu.obs.devprof` —
  the runtime cost ledger (ISSUE 14): per-dispatch device-time
  attribution keyed by compile-budget program names, live-vs-padded
  lane occupancy, compile-event tracing into ``compile_ledger.jsonl``,
  and on-demand ``jax.profiler`` windows (``/profilez``,
  ``bench.py --device-trace``) merged into the Perfetto timeline.
* :mod:`~go_ibft_tpu.obs.gates` — regression gates comparing a fresh
  evidence file against the best prior ``BENCH_r*.json`` per config on the
  same backend (``scripts/obs_report.py`` / ``make obs-report``), so
  CPU-fallback rounds still catch regressions without a chip.
"""

from . import clock, devprof, ledger, trace
from .evidence import EvidenceWriter, Fingerprint, probe_fingerprint
from .export import to_chrome_trace, write_chrome_trace
from .gates import GateResult, gate_evidence, gate_slo_records, render_table
from .httpd import TelemetryServer
from .metrics_export import render_prometheus
from .recorder import RingRecorder

__all__ = [
    "clock",
    "devprof",
    "ledger",
    "trace",
    "EvidenceWriter",
    "Fingerprint",
    "probe_fingerprint",
    "to_chrome_trace",
    "write_chrome_trace",
    "GateResult",
    "gate_evidence",
    "gate_slo_records",
    "render_table",
    "RingRecorder",
    "TelemetryServer",
    "render_prometheus",
]
