"""Prometheus text-exposition rendering of the process metrics registry.

Zero-dependency (like everything in ``obs/``): walks
:mod:`go_ibft_tpu.utils.metrics` — gauges, monotonic counters, the
windowed deque histograms (rendered as ``_p50``/``_p99``/``_mean``/
``_max``/``_window_count`` gauges: their bounded window breaks true
summary semantics, so they are labeled for what they are), and the
fixed-bucket latency histograms (proper Prometheus ``histogram`` families
with cumulative ``_bucket{le=...}`` lines, ``_sum`` and ``_count``) —
into the text format every Prometheus-compatible scraper ingests
(``text/plain; version=0.0.4``).

Metric naming: a registry key tuple's first three parts become the metric
name (sanitized, joined with ``_``); any remaining parts become a ``tag``
label, so per-route / per-tenant keys like
``("go-ibft", "latency", "verify_drain_ms", "host")`` render as one
family ``go_ibft_latency_verify_drain_ms{tag="host"}`` with one series
per tag.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils import metrics

__all__ = ["render_prometheus", "metric_name", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_ESCAPE = str.maketrans({"\\": r"\\", '"': r"\"", "\n": r"\n"})


def _sanitize(part: str) -> str:
    clean = _SANITIZE.sub("_", str(part))
    if clean and clean[0].isdigit():
        clean = "_" + clean
    return clean


def metric_name(key: Tuple[str, ...]) -> Tuple[str, Optional[str]]:
    """Registry key -> (prometheus metric name, optional ``tag`` label)."""
    head = key[:3] if len(key) > 3 else key
    name = "_".join(_sanitize(p) for p in head)
    tag = "_".join(str(p) for p in key[3:]) if len(key) > 3 else None
    return name, tag


def _series(name: str, tag: Optional[str], extra: str = "") -> str:
    labels = []
    if tag is not None:
        labels.append(f'tag="{tag.translate(_LABEL_ESCAPE)}"')
    if extra:
        labels.append(extra)
    return f"{name}{{{','.join(labels)}}}" if labels else name


def _fmt(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(float(value))


def _percentile(ordered: List[float], q: float) -> float:
    value = metrics.percentile(ordered, q)
    return 0.0 if value is None else value


def render_prometheus() -> str:
    """The full registry as Prometheus text exposition (one scrape)."""
    lines: List[str] = []
    typed: set = set()

    def emit_type(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    # Gauges.
    gauges = metrics.gauges_snapshot()
    counters = metrics.counters_snapshot()
    windows = metrics.histograms_snapshot()
    for key in sorted(gauges):
        name, tag = metric_name(key)
        emit_type(name, "gauge")
        lines.append(f"{_series(name, tag)} {_fmt(gauges[key])}")

    # Monotonic counters.
    for key in sorted(counters):
        name, tag = metric_name(key)
        name += "_total"
        emit_type(name, "counter")
        lines.append(f"{_series(name, tag)} {counters[key]}")

    # Windowed deque histograms: summary-ish gauges over the window.
    for key in sorted(windows):
        samples = sorted(windows[key])
        if not samples:
            continue
        name, tag = metric_name(key)
        for suffix, value in (
            ("_p50", _percentile(samples, 0.50)),
            ("_p99", _percentile(samples, 0.99)),
            ("_mean", sum(samples) / len(samples)),
            ("_max", samples[-1]),
            ("_window_count", float(len(samples))),
        ):
            emit_type(name + suffix, "gauge")
            lines.append(f"{_series(name + suffix, tag)} {_fmt(value)}")

    # Fixed-bucket histograms: real Prometheus histogram families.
    fixed = metrics.fixed_histograms_snapshot()
    for key in sorted(fixed):
        name, tag = metric_name(key)
        hist = fixed[key]
        emit_type(name, "histogram")
        cumulative = 0
        for bound, count in zip(hist["bounds"], hist["counts"]):
            cumulative += count
            le = 'le="' + _fmt(float(bound)) + '"'
            lines.append(f"{_series(name + '_bucket', tag, le)} {cumulative}")
        inf = 'le="+Inf"'
        lines.append(f"{_series(name + '_bucket', tag, inf)} {hist['count']}")
        lines.append(f"{_series(name + '_sum', tag)} {_fmt(hist['sum'])}")
        lines.append(f"{_series(name + '_count', tag)} {hist['count']}")

    _render_ledger(lines, emit_type)
    return "\n".join(lines) + "\n"


def _render_ledger(lines: List[str], emit_type) -> None:
    """Cost-ledger families (ISSUE 14): per-(program, route) dispatch
    counters + occupancy gauges and per-program compile accumulators.
    Nothing renders while the ledger is disabled."""
    from . import ledger as cost_ledger

    snap = cost_ledger.snapshot()
    if snap is None:
        return

    def esc(v: str) -> str:
        return str(v).translate(_LABEL_ESCAPE)

    # Family-major iteration: a family's series must form ONE contiguous
    # group after its TYPE line (the text-format grouping rule strict
    # scrapers enforce) — same discipline as the renderers above.
    rows = snap["dispatches"]
    labels = [
        f'program="{esc(row["program"])}",route="{esc(row["route"])}"'
        for row in rows
    ]
    for family, field, kind in (
        ("go_ibft_ledger_dispatches_total", "dispatches", "counter"),
        ("go_ibft_ledger_lanes_live_total", "live_lanes", "counter"),
        ("go_ibft_ledger_lanes_padded_total", "padded_lanes", "counter"),
        ("go_ibft_ledger_device_ms_total", "device_ms", "counter"),
        ("go_ibft_ledger_occupancy", "occupancy", "gauge"),
    ):
        for row, label in zip(rows, labels):
            value = row[field]
            if value is None:
                continue
            emit_type(family, kind)
            lines.append(f"{family}{{{label}}} {_fmt(float(value))}")
    for family, field in (
        ("go_ibft_ledger_compiles_total", "count"),
        ("go_ibft_ledger_compile_ms_total", "ms"),
    ):
        for program in sorted(snap["compiles"]):
            acc = snap["compiles"][program]
            emit_type(family, "counter")
            lines.append(
                f"{family}{{program=\"{esc(program)}\"}} {_fmt(float(acc[field]))}"
            )


def parse_exposition(text: str) -> Dict[str, float]:
    """Minimal parser for tests and the smoke scraper: series -> value.

    Validates the shape as it goes (every non-comment line must be
    ``<series> <number>``) — raises ``ValueError`` on anything a real
    Prometheus scraper would reject.
    """
    out: Dict[str, float] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(" ", 1)
        if len(parts) != 2:
            raise ValueError(f"malformed exposition line: {raw!r}")
        series, value = parts
        out[series] = float(value)
    return out
