"""Hang-proof evidence capture: cached probe fingerprint + JSONL writer.

Supersedes ``go_ibft_tpu.bench.evidence`` (which now re-exports from
here).  Two jobs:

**1. The backend fingerprint.**  Five rounds of history say the tunneled
TPU backend can hang ``jax.devices()`` indefinitely (BENCH_r04: three
120 s probe timeouts; the r05 driver re-probed for 580 s and never got an
answer).  The bench process must therefore *never* call ``jax.devices()``
on the default backend itself before a subprocess proved it alive:
:func:`probe_fingerprint` runs the shared subprocess probe
(:mod:`go_ibft_tpu.utils.probe` — hard wall-clock deadline, the parent
decides) and memoizes the verdict to ``~/.cache/go_ibft_tpu/probe.json``
with a TTL, so repeated probe points within a TTL window cost one file
read instead of one timeout each.  ``--reprobe`` (or ``reprobe=True``)
bypasses the cache.  The fingerprint records *how* the verdict was
obtained — ``probe: ok|timeout|error|cached`` — and every evidence line
carries it.

**2. The evidence writer.**  Append-only JSONL, one line per record,
flushed (and fsync'd) per record, so a run killed mid-config still leaves
every completed config's evidence on disk.  Each line carries the
required schema fields (``config``, ``metric``, ``backend``, ``probe``,
``ts``) on top of whatever the caller measured; ``bench.py`` reserves
rc=0 strictly for "every config produced an evidence line".
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import asdict, dataclass
from typing import List, Optional, Sequence, Tuple

from ..utils.probe import probe_default_backend_full

__all__ = [
    "Fingerprint",
    "probe_fingerprint",
    "EvidenceWriter",
    "REQUIRED_EVIDENCE_FIELDS",
    "EVIDENCE_PATH",
    "reprobe_and_capture",
]

# Platforms that count as the target accelerator ("axon" = the tunneled
# TPU PJRT plugin).
TPU_PLATFORMS = ("tpu", "axon")

DEFAULT_TTL_S = 900.0

REQUIRED_EVIDENCE_FIELDS = ("config", "metric", "backend", "probe", "ts")


def _cache_path() -> str:
    override = os.environ.get("GO_IBFT_PROBE_CACHE")
    if override:
        return override
    return os.path.join(
        os.path.expanduser("~"), ".cache", "go_ibft_tpu", "probe.json"
    )


@dataclass
class Fingerprint:
    """One probe verdict: what the default JAX backend looked like, when,
    and how we found out."""

    platform: Optional[str]  # live default platform, or None when dead
    probe: str  # "ok" | "timeout" | "error" | "cached"
    detail: str  # one-line reason for the log
    probed_at: float  # epoch seconds of the underlying probe
    # Environment pin: a fingerprint probed under an explicit platform pin
    # (JAX_PLATFORMS) must not serve a differently-pinned process.
    env_platforms: str = ""
    # How many devices the probed backend exposed (None when dead or when
    # the probe stub predates the DEVICES= line).  Evidence lines carry it
    # so dp=1 and dp>1 runs of the mesh configs are distinguishable.
    device_count: Optional[int] = None

    def is_tpu(self) -> bool:
        return self.platform in TPU_PLATFORMS

    def backend_label(self) -> str:
        """The evidence-schema backend field: ``tpu`` or ``cpu-fallback``."""
        return "tpu" if self.is_tpu() else "cpu-fallback"


def _load_cached(path: str, ttl_s: float) -> Optional[Fingerprint]:
    try:
        with open(path) as fh:
            raw = json.load(fh)
        fp = Fingerprint(**raw)
    except (OSError, ValueError, TypeError):
        return None
    if time.time() - fp.probed_at > ttl_s:
        return None
    if fp.env_platforms != os.environ.get("JAX_PLATFORMS", ""):
        return None
    return fp


def _store_cached(path: str, fp: Fingerprint) -> None:
    # Best-effort atomic write: a read-only $HOME must never cost the
    # caller its probe verdict, and a concurrent reader must never see a
    # torn file.
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        with os.fdopen(fd, "w") as fh:
            json.dump(asdict(fp), fh)
        os.replace(tmp, path)
    except OSError:
        pass


def probe_fingerprint(
    timeout_s: Optional[float] = None,
    *,
    ttl_s: Optional[float] = None,
    reprobe: bool = False,
    cache_path: Optional[str] = None,
) -> Fingerprint:
    """Probe the default backend, via the TTL'd on-disk cache.

    A fresh cached verdict (younger than ``ttl_s``, same ``JAX_PLATFORMS``
    pin) is returned with ``probe="cached"`` without spawning anything;
    otherwise the subprocess probe runs under its hard deadline and the
    verdict — alive, dead, or hung-past-deadline — is cached for the next
    probe point.  This function can never block past ``timeout_s``: the
    probe is a subprocess the parent kills, never an in-process
    ``jax.devices()`` call.
    """
    path = cache_path if cache_path is not None else _cache_path()
    if ttl_s is None:
        ttl_s = float(os.environ.get("GO_IBFT_PROBE_TTL", DEFAULT_TTL_S))
    if not reprobe:
        cached = _load_cached(path, ttl_s)
        if cached is not None:
            cached.probe = "cached"
            return cached
    platform, detail, device_count = probe_default_backend_full(timeout_s)
    if platform is not None:
        status = "ok"
    elif detail.startswith("probe timeout"):
        status = "timeout"
    else:
        status = "error"
    fp = Fingerprint(
        platform=platform,
        probe=status,
        detail=detail,
        probed_at=time.time(),
        env_platforms=os.environ.get("JAX_PLATFORMS", ""),
        device_count=device_count,
    )
    _store_cached(path, fp)
    return fp


class EvidenceWriter:
    """Append-only, per-record-flushed JSONL evidence file.

    The file handle opens in append mode and every :meth:`record` call
    writes exactly one line, flushes, and fsyncs — a crash (or a driver
    SIGKILL) after config N loses nothing before N+1.  ``backend`` and
    ``probe`` (the schema's provenance fields) are stamped on every line
    from the fingerprint the run started with; callers may override per
    record (the late TPU re-probe does).
    """

    def __init__(
        self,
        path: str,
        *,
        backend: str = "cpu-fallback",
        probe: str = "error",
        devices: Optional[int] = None,
        truncate: bool = False,
    ) -> None:
        self.path = path
        self.backend = backend
        self.probe = probe
        # Probed device count (Fingerprint.device_count): stamped on every
        # line so mesh-config evidence distinguishes dp=1 from dp>1 runs.
        self.devices = devices
        self._fh = open(path, "w" if truncate else "a")
        self._configs: List[str] = []
        # Cost-ledger totals at the previous record (ISSUE 14): each line
        # carries the DELTA since the line before it, so per-config
        # dispatch counts and occupancy are readable straight off the
        # evidence and obs/gates.py can regression-gate their growth.
        self._ledger_last: Optional[dict] = None

    def set_provenance(
        self, backend: str, probe: str, devices: Optional[int] = None
    ) -> None:
        self.backend = backend
        self.probe = probe
        if devices is not None:
            self.devices = devices

    def record(self, config: str, line: Optional[dict] = None, **fields) -> dict:
        """Append one evidence line for ``config``; returns the full record."""
        rec = dict(line or {})
        rec.update(fields)
        rec.setdefault("metric", config)
        rec["config"] = config
        rec.setdefault("backend", self.backend)
        rec.setdefault("probe", self.probe)
        rec.setdefault("devices", self.devices)
        block = self._ledger_block()
        if block is not None:
            rec.setdefault("ledger", block)
        rec["ts"] = time.time()
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        try:
            os.fsync(self._fh.fileno())
        except OSError:
            pass
        self._configs.append(config)
        return rec

    def _ledger_block(self) -> Optional[dict]:
        """Cost-ledger delta since the previous record (None when the
        ledger is off): dispatches, device_ms, live/padded lanes + the
        derived occupancy, compiles, compile_ms — the per-config stamp
        ``scripts/obs_report.py`` / ``obs/gates.py`` regression-gate."""
        from . import ledger as cost_ledger

        cur = cost_ledger.totals()
        if cur is None:
            self._ledger_last = None
            return None
        prev = self._ledger_last or {}
        self._ledger_last = cur
        block = {
            key: round(cur[key] - prev.get(key, 0), 3)
            for key in (
                "dispatches",
                "live_lanes",
                "padded_lanes",
                "device_ms",
                "compiles",
                "compile_ms",
            )
        }
        block["occupancy"] = (
            round(block["live_lanes"] / block["padded_lanes"], 4)
            if block["padded_lanes"]
            else None
        )
        return block

    def covered(self) -> List[str]:
        """Configs recorded so far, in order."""
        return list(self._configs)

    def missing(self, expected: Sequence[str]) -> List[str]:
        seen = set(self._configs)
        return [c for c in expected if c not in seen]

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass

    def __enter__(self) -> "EvidenceWriter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


# ---------------------------------------------------------------------------
# Opportunistic TPU capture (absorbed from go_ibft_tpu/bench/evidence.py)
# ---------------------------------------------------------------------------

EVIDENCE_PATH = "evidence_tpu.jsonl"


def reprobe_and_capture(
    remaining_s: float,
    bench_path: str,
    evidence_path: str = EVIDENCE_PATH,
) -> Tuple[Optional[str], str]:
    """Late re-probe; on a live TPU, run ``bench.py`` in a subprocess.

    A CPU-fallback bench run re-probes near its end (bypassing the
    fingerprint cache — the whole point is catching a tunnel that woke up
    AFTER the startup probe) and, when the default backend came alive,
    relaunches the bench in a FRESH subprocess — the fallback process
    pinned ``jax_platforms=cpu`` at startup and can never use the chip
    itself — appending the child's JSON lines to ``evidence_tpu.jsonl``.

    Returns ``(platform_or_None, detail)``: platform is the live TPU
    platform name when evidence was captured (detail names the artifact),
    else ``None`` with a one-line reason.  Budget discipline mirrors the
    parent: the probe is clamped well under ``remaining_s`` and the child
    gets what is left minus a reserve, so the parent always finishes its
    own report.
    """
    if remaining_s < 240.0:
        return None, f"skipped: {remaining_s:.0f}s of budget left"
    fp = probe_fingerprint(min(45.0, remaining_s * 0.15), reprobe=True)
    if not fp.is_tpu():
        return None, (
            fp.detail if fp.platform is None else f"backend is {fp.platform!r}"
        )
    child_budget = max(120.0, remaining_s - 90.0)
    env = dict(os.environ, GO_IBFT_BENCH_BUDGET_S=str(int(child_budget)))
    env.pop("JAX_PLATFORMS", None)  # the child must see the live default
    # The child gets its OWN per-config evidence file: it would otherwise
    # inherit (or re-derive) the parent's path and TRUNCATE it mid-run —
    # the parent still holds an open append handle and has configs left
    # to record.  The tpu-side evidence sits next to the stdout capture.
    env["GO_IBFT_EVIDENCE_PATH"] = evidence_path + ".configs.jsonl"
    try:
        with open(evidence_path, "a") as fh:
            subprocess.run(
                [sys.executable, bench_path],
                stdout=fh,
                stderr=subprocess.DEVNULL,
                timeout=child_budget + 30.0,
                env=env,
                cwd=os.path.dirname(os.path.abspath(bench_path)) or ".",
                check=False,
            )
    except (OSError, subprocess.TimeoutExpired) as err:
        return None, f"evidence run failed: {type(err).__name__}"
    return fp.platform, evidence_path
