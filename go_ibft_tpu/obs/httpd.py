"""Zero-dependency telemetry HTTP endpoints: /metrics, /healthz, /statusz,
/profilez.

Stdlib ``http.server`` only, like everything else in this repo — a
:class:`TelemetryServer` binds a ``ThreadingHTTPServer`` on localhost (or
a given host) and serves:

* ``GET /metrics``  — Prometheus text exposition of the whole metrics
  registry (:mod:`go_ibft_tpu.obs.metrics_export`), cost-ledger families
  included when the ledger is enabled;
* ``GET /healthz``  — liveness JSON from the mounted ``health_fn``;
  HTTP 200 when healthy, 503 when not (a wedged runner flips this — the
  probe a fleet orchestrator restarts on);
* ``GET /readyz``   — readiness JSON from the mounted ``ready_fn``:
  200 only once the node may be routed traffic (recovered + first
  height finalized), 503 before that.  Distinct from liveness on
  purpose — a warm-starting node is alive (do not restart it) but not
  ready (do not send it clients yet); supervisors probe the two
  endpoints for the two decisions.  With no ``ready_fn`` mounted the
  endpoint reports ready (a mount that never warms has nothing to
  gate);
* ``GET /statusz``  — operator status JSON from ``status_fn`` (current
  height/round, breaker level, speculation hit rate, cache stats, ring
  ``dropped`` — whatever the mounting component provides), plus a
  ``cost_ledger`` block (dispatch/occupancy/compile totals) whenever the
  runtime cost ledger is on;
* ``GET /profilez?seconds=0.5`` — an on-demand ``jax.profiler`` window
  (:mod:`go_ibft_tpu.obs.devprof`): captures device activity for the
  given window and returns the trace path + host-clock anchor, ready for
  ``obs/timeline.py::merge_device_trace``.  409 when a window is already
  open, 503 when the profiler is unavailable.  The ONLY non-read-only
  endpoint — it writes a trace file to a temp dir, never touches
  consensus state.

Endpoints are default-off: nothing in the hot path starts a server;
``ChainRunner.start_telemetry`` (or an embedder) mounts one explicitly,
and the handler threads only ever read lock-guarded snapshots, so a
scrape can never block consensus.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple
from urllib.parse import parse_qs

from . import metrics_export
from . import ledger as cost_ledger

__all__ = ["TelemetryServer"]

StatusFn = Callable[[], dict]
HealthFn = Callable[[], Tuple[bool, dict]]
ReadyFn = Callable[[], Tuple[bool, dict]]


class _Handler(BaseHTTPRequestHandler):
    server_version = "go-ibft-telemetry/1"
    # The outer TelemetryServer injects these per server class (below).
    status_fn: Optional[StatusFn] = None
    health_fn: Optional[HealthFn] = None
    ready_fn: Optional[ReadyFn] = None

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        path, _, query = self.path.partition("?")
        try:
            if path == "/metrics":
                body = metrics_export.render_prometheus().encode("utf-8")
                self._reply(200, metrics_export.CONTENT_TYPE, body)
            elif path == "/healthz":
                ok, payload = (
                    self.health_fn() if self.health_fn is not None else (True, {})
                )
                payload = dict(payload)
                payload.setdefault("ok", ok)
                self._reply_json(200 if ok else 503, payload)
            elif path == "/readyz":
                ready, payload = (
                    self.ready_fn() if self.ready_fn is not None else (True, {})
                )
                payload = dict(payload)
                payload.setdefault("ready", ready)
                self._reply_json(200 if ready else 503, payload)
            elif path == "/statusz":
                payload = self.status_fn() if self.status_fn is not None else {}
                payload = dict(payload)
                # The ledger block rides every mount uniformly (runner,
                # bench, embedder) — None distinguishes "ledger off" from
                # "ledger on, nothing recorded".
                payload.setdefault("cost_ledger", cost_ledger.status())
                self._reply_json(200, payload)
            elif path == "/profilez":
                self._profilez(query)
            else:
                self._reply_json(404, {"error": "not found", "path": path})
        except Exception as err:  # noqa: BLE001 - a scrape must never crash
            # the serving thread; surface the failure to the scraper.
            try:
                self._reply_json(500, {"error": repr(err)})
            except OSError:
                pass  # client went away mid-error: nothing left to do

    def _profilez(self, query: str) -> None:
        """On-demand device-profiler window (see module docstring)."""
        from . import devprof

        params = parse_qs(query)
        try:
            seconds = float(params.get("seconds", ["0.5"])[0])
        except ValueError:
            self._reply_json(400, {"error": "seconds must be a number"})
            return
        result = devprof.capture(seconds)
        if result.get("ok"):
            code = 200
        elif str(result.get("error", "")).startswith("busy"):
            code = 409
        else:
            code = 503
        self._reply_json(code, result)

    def _reply(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, payload: dict) -> None:
        self._reply(
            code,
            "application/json",
            json.dumps(payload, default=str).encode("utf-8"),
        )

    def log_message(self, fmt: str, *args) -> None:  # silence per-request spam
        pass


class TelemetryServer:
    """Threaded localhost telemetry endpoint mount.

    ``port=0`` binds an ephemeral port (read it back from ``.port`` after
    :meth:`start`).  ``status_fn``/``health_fn`` are called per request on
    a handler thread — they must be cheap, thread-safe reads.
    """

    def __init__(
        self,
        *,
        status_fn: Optional[StatusFn] = None,
        health_fn: Optional[HealthFn] = None,
        ready_fn: Optional[ReadyFn] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self._host = host
        self._want_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        # Per-instance handler class so two servers in one process can
        # mount different status providers.
        self._handler = type(
            "_BoundHandler",
            (_Handler,),
            {"status_fn": staticmethod(status_fn) if status_fn else None,
             "health_fn": staticmethod(health_fn) if health_fn else None,
             "ready_fn": staticmethod(ready_fn) if ready_fn else None},
        )

    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        if self._httpd is not None:
            raise RuntimeError("TelemetryServer already started")
        self._httpd = ThreadingHTTPServer(
            (self._host, self._want_port), self._handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"telemetry-{self.port}",
            daemon=True,
        )
        self._thread.start()
        return self.port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
