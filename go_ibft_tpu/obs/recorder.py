"""Fixed-size ring buffer for finished spans and instant events.

The flight-recorder storage: a preallocated ring of record tuples guarded
by one lock.  Appends are O(1) and never allocate beyond the tuple being
stored; when the ring is full the oldest records are overwritten — a
flight recorder keeps the *latest* window, which is the one that explains
a hang or a slow drain.  Nothing here imports JAX, asyncio, or any other
framework: the recorder must be safe to call from transport threads,
worker pools, and the engine's event loop alike.

Record layout (plain tuples — cheap to create, cheap to drain)::

    (ph, name, track, ts_us, dur_us, args)

``ph`` is the Chrome trace-event phase this record exports as: ``"X"``
(complete span) or ``"i"`` (instant).  ``ts_us`` is a monotonic
microsecond timestamp (``time.perf_counter_ns() // 1000`` — one shared
clock for every record, so cross-track ordering is meaningful).  ``args``
is a (possibly empty) dict of span attributes.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

Record = Tuple[str, str, str, int, int, Optional[dict]]

DEFAULT_CAPACITY = 65536


class RingRecorder:
    """Thread-safe fixed-capacity ring of trace records."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._buf: List[Optional[Record]] = [None] * capacity
        self._next = 0  # total records ever appended
        self.dropped = 0  # records overwritten after the ring filled

    def append(self, record: Record) -> None:
        with self._lock:
            i = self._next % self.capacity
            if self._buf[i] is not None:
                self.dropped += 1
            self._buf[i] = record
            self._next += 1

    def __len__(self) -> int:
        with self._lock:
            return min(self._next, self.capacity)

    def snapshot(self) -> List[Record]:
        """All retained records, oldest first (does not clear)."""
        with self._lock:
            if self._next <= self.capacity:
                return [r for r in self._buf[: self._next] if r is not None]
            i = self._next % self.capacity
            return [r for r in self._buf[i:] + self._buf[:i] if r is not None]

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._next = 0
            self.dropped = 0
