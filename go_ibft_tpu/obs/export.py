"""Chrome ``trace_event`` / Perfetto JSON export for the flight recorder.

Produces the JSON-object flavor of the trace-event format (the one both
``chrome://tracing`` and https://ui.perfetto.dev load directly)::

    {
      "displayTimeUnit": "ms",
      "traceEvents": [
        {"ph": "M", "pid": 0, "tid": 3, "name": "thread_name",
         "args": {"name": "node-1a2b3c"}},
        {"ph": "X", "pid": 0, "tid": 3, "name": "commit.drain",
         "cat": "obs", "ts": 12345, "dur": 210, "args": {"round": 0}},
        {"ph": "i", "pid": 0, "tid": 3, "name": "round.timeout",
         "cat": "obs", "ts": 99999, "s": "t", "args": {"round": 1}}
      ]
    }

Each recorder *track* becomes one ``tid`` with a ``thread_name`` metadata
event, so a 6-node height renders as six labeled rows.  Timestamps are the
recorder's shared monotonic microsecond clock, rebased to the earliest
record so traces start near zero.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

from .recorder import Record, RingRecorder

__all__ = ["to_chrome_trace", "write_chrome_trace"]

_PID = 0
_CAT = "obs"


def to_chrome_trace(
    records: Iterable[Record],
    dropped: int = 0,
    *,
    node: Optional[str] = None,
    clock_offsets: Optional[dict] = None,
) -> dict:
    """Recorder records -> a Chrome trace-event JSON object (as a dict).

    ``dropped`` (records overwritten after the ring filled) is surfaced in
    the document's ``otherData`` so a truncated flight-recorder window is
    visible in the artifact itself: spans near the wrap boundary may have
    lost their children, and tooling must not treat such a trace as a
    complete record.

    ``node`` stamps the exporting process's node identity and
    ``clock_offsets`` its per-peer clock-offset estimates
    (:mod:`go_ibft_tpu.obs.clock` snapshot) into ``otherData`` — the
    cross-process telemetry plane's export contract: the timeline tool
    merges N per-node files and needs both to rebase foreign timestamps.
    A loopback export carries an empty offsets map (one shared clock).
    """
    records = list(records)
    base = min((r[3] for r in records), default=0)
    tids: Dict[str, int] = {}
    events: List[dict] = []
    for ph, name, track, ts_us, dur_us, args in records:
        tid = tids.get(track)
        if tid is None:
            tid = tids[track] = len(tids)
            events.append(
                {
                    "ph": "M",
                    "pid": _PID,
                    "tid": tid,
                    "name": "thread_name",
                    "args": {"name": track},
                }
            )
        event = {
            "ph": ph,
            "pid": _PID,
            "tid": tid,
            "name": name,
            "cat": _CAT,
            "ts": ts_us - base,
            "args": args or {},
        }
        if ph == "X":
            event["dur"] = dur_us
        elif ph == "i":
            event["s"] = "t"  # thread-scoped instant
        events.append(event)
    # Raw-clock anchor of the rebase: ts 0 in this document is this raw
    # monotonic microsecond.  Device-trace merging (obs/timeline.py::
    # merge_device_trace) uses it to place a jax.profiler window — whose
    # own timestamps are session-relative — onto this document's clock.
    other = {"droppedRecords": dropped, "clockBaseUs": base}
    if node is not None:
        other["node"] = node
    if clock_offsets is not None:
        other["clockOffsetsUs"] = clock_offsets
    return {
        "displayTimeUnit": "ms",
        "otherData": other,
        "traceEvents": events,
    }


def write_chrome_trace(
    path: str,
    recorder: Optional[RingRecorder] = None,
    *,
    node: Optional[str] = None,
    clock_offsets: Optional[dict] = None,
) -> int:
    """Export ``recorder`` (default: the active trace recorder) to ``path``.

    Returns the number of trace events written (metadata included).  An
    empty or missing recorder still writes a valid empty trace, so a
    ``--trace`` run that recorded nothing leaves a loadable artifact
    rather than a crash.  ``clock_offsets`` defaults to the process-global
    :mod:`~go_ibft_tpu.obs.clock` snapshot whenever ``node`` is given (a
    per-node export is exactly the cross-process case that needs it).
    """
    if recorder is None:
        from . import trace

        recorder = trace.recorder()
    if clock_offsets is None and node is not None:
        from . import clock

        clock_offsets = clock.snapshot()
    doc = to_chrome_trace(
        recorder.snapshot() if recorder is not None else [],
        dropped=recorder.dropped if recorder is not None else 0,
        node=node,
        clock_offsets=clock_offsets,
    )
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return len(doc["traceEvents"])
