"""Chrome ``trace_event`` / Perfetto JSON export for the flight recorder.

Produces the JSON-object flavor of the trace-event format (the one both
``chrome://tracing`` and https://ui.perfetto.dev load directly)::

    {
      "displayTimeUnit": "ms",
      "traceEvents": [
        {"ph": "M", "pid": 0, "tid": 3, "name": "thread_name",
         "args": {"name": "node-1a2b3c"}},
        {"ph": "X", "pid": 0, "tid": 3, "name": "commit.drain",
         "cat": "obs", "ts": 12345, "dur": 210, "args": {"round": 0}},
        {"ph": "i", "pid": 0, "tid": 3, "name": "round.timeout",
         "cat": "obs", "ts": 99999, "s": "t", "args": {"round": 1}}
      ]
    }

Each recorder *track* becomes one ``tid`` with a ``thread_name`` metadata
event, so a 6-node height renders as six labeled rows.  Timestamps are the
recorder's shared monotonic microsecond clock, rebased to the earliest
record so traces start near zero.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

from .recorder import Record, RingRecorder

__all__ = ["to_chrome_trace", "write_chrome_trace"]

_PID = 0
_CAT = "obs"


def to_chrome_trace(records: Iterable[Record], dropped: int = 0) -> dict:
    """Recorder records -> a Chrome trace-event JSON object (as a dict).

    ``dropped`` (records overwritten after the ring filled) is surfaced in
    the document's ``otherData`` so a truncated flight-recorder window is
    visible in the artifact itself: spans near the wrap boundary may have
    lost their children, and tooling must not treat such a trace as a
    complete record.
    """
    records = list(records)
    base = min((r[3] for r in records), default=0)
    tids: Dict[str, int] = {}
    events: List[dict] = []
    for ph, name, track, ts_us, dur_us, args in records:
        tid = tids.get(track)
        if tid is None:
            tid = tids[track] = len(tids)
            events.append(
                {
                    "ph": "M",
                    "pid": _PID,
                    "tid": tid,
                    "name": "thread_name",
                    "args": {"name": track},
                }
            )
        event = {
            "ph": ph,
            "pid": _PID,
            "tid": tid,
            "name": name,
            "cat": _CAT,
            "ts": ts_us - base,
            "args": args or {},
        }
        if ph == "X":
            event["dur"] = dur_us
        elif ph == "i":
            event["s"] = "t"  # thread-scoped instant
        events.append(event)
    return {
        "displayTimeUnit": "ms",
        "otherData": {"droppedRecords": dropped},
        "traceEvents": events,
    }


def write_chrome_trace(
    path: str, recorder: Optional[RingRecorder] = None
) -> int:
    """Export ``recorder`` (default: the active trace recorder) to ``path``.

    Returns the number of trace events written (metadata included).  An
    empty or missing recorder still writes a valid empty trace, so a
    ``--trace`` run that recorded nothing leaves a loadable artifact
    rather than a crash.
    """
    if recorder is None:
        from . import trace

        recorder = trace.recorder()
    doc = to_chrome_trace(
        recorder.snapshot() if recorder is not None else [],
        dropped=recorder.dropped if recorder is not None else 0,
    )
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return len(doc["traceEvents"])
