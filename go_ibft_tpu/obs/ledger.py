"""Process-wide runtime cost ledger: per-dispatch device-time attribution,
compile-event tracing, and occupancy accounting.

ISSUE 14 tentpole.  Nine bench rounds can say *what* ran but not *where
device time or padding went*: the verify plane buckets lanes to power-of-
two shapes (padding waste unmeasured), every subsystem keeps its own
one-off dispatch counter, and a cold XLA compile — the single worst
production number (BENCH_r04's ~3-minute quorum_certify build) — leaves
no record of which program compiled, when, or for how long.  This module
is the one attributed accounting plane behind all of it:

* **Dispatch records.**  Every jit/shard_map launch seam
  (``verify/batch.py``, ``verify/mesh_batch.py``, ``verify/aggregate.py``,
  ``verify/pipeline.py``, ``sched/dispatch.py``, ``serve/server.py``,
  ``ops/bls12_381.py``, ``net/aggtree.py``) records *program identity*,
  *route*, lane counts split **live vs padded** (occupancy — the fraction
  of a padded bucket doing real work), and wall/block-until-ready
  duration into bounded per-``(program, route)`` accumulators.

* **Program identity IS the compile-budget key space.**  Dispatch records
  use the family names of the ``scripts/compile_budget.py`` registry
  (``quorum_certify``, ``round_certify``, ``ecdsa_recover``,
  ``mesh_verify_mask``, ``bls_aggregate_verify``, ``bls_g2_merge_tree``,
  ``bls_multipair_miller``, ...) with the shape suffix dropped — so
  ``scripts/cost_report.py`` can attribute recorded dispatches straight
  onto the pinned program set, and the AOT manifest of ROADMAP item 5
  and this ledger agree on what a "program" is.  A *route* names the
  engine that served the lanes (``device`` / ``mesh`` / ``host`` /
  ``python`` / ``warmup``), optionally prefixed by a caller tag
  (:func:`route_tag`) so e.g. the serve plane's drains read
  ``serve/device``.

* **Compile-event tracing.**  Dispatch spans watch their jit objects'
  compiled-program caches (``PjitFunction._cache_size`` — cold vs warm
  detection by introspection, with the span's wall time as the measured
  first-dispatch duration) and append one record per compilation to an
  append-only ``compile_ledger.jsonl``: program, duration, call-site.
  That file is the precursor manifest for the ROADMAP item 5 AOT cache —
  it lists exactly which programs a process compiled and what each cost.

Disabled mode is ONE predicate check (the :mod:`~go_ibft_tpu.obs.trace`
rule): every instrumentation entry point reads one module global and
returns a shared no-op immediately — no clock reads, no numpy, no lane
counting.  ``tests/test_bench_contract.py`` pins the resulting overhead
under 5% of the config #1 happy path alongside the tracing/histogram
pins.  Thread-safe: accumulators are lock-guarded, the compile log is
flushed per record, and the route tag rides a ``contextvars.ContextVar``
so transport threads and the engine loop never interleave tags.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CostLedger",
    "enable",
    "disable",
    "enabled",
    "get",
    "record_dispatch",
    "add_device_ms",
    "record_compile",
    "dispatch_span",
    "compile_watch",
    "route_tag",
    "jit_cache_size",
    "snapshot",
    "totals",
    "status",
    "OVERFLOW_PROGRAM",
]

# Bounded key space: a runaway program-name generator (e.g. a bug that
# interpolates a height into the name) must not leak memory; past the cap
# new keys accumulate under one overflow bucket, counted.
DEFAULT_MAX_PROGRAMS = 256
OVERFLOW_PROGRAM = "_other"

# THE predicate: every instrumentation site checks this one global.
_ledger: Optional["CostLedger"] = None

# Caller tag prepended to routes ("serve", "aggtree", ...): set by the
# consuming subsystem around its drains so shared seams attribute to it.
_route_tag: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "go_ibft_ledger_route_tag", default=None
)


def jit_cache_size(fn) -> Optional[int]:
    """Compiled-program count of a ``jax.jit`` object (None when the
    object exposes no cache — plain functions, test stubs, older jax)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001 - introspection must never raise
        return None


class CostLedger:
    """The accumulator store.  Use the module-level helpers at seams —
    they carry the one-predicate disabled path; this class assumes it is
    live."""

    def __init__(
        self,
        *,
        compile_log: Optional[str] = None,
        max_programs: int = DEFAULT_MAX_PROGRAMS,
    ) -> None:
        self._lock = threading.Lock()
        # (program, route) -> [dispatches, live_lanes, padded_lanes, device_ms]
        self._stats: Dict[Tuple[str, str], list] = {}
        # program -> [compiles, compile_ms]
        self._compiles: Dict[str, list] = {}
        self._max = max_programs
        self.compile_log_path = compile_log
        self._compile_fh = None
        self.overflowed = 0

    # -- recording ------------------------------------------------------

    @staticmethod
    def _effective_route(route: str) -> str:
        tag = _route_tag.get()
        return route if tag is None else f"{tag}/{route}"

    def _slot(self, program: str, route: str) -> list:
        # Caller holds the lock.
        key = (program, route)
        slot = self._stats.get(key)
        if slot is None:
            if len(self._stats) >= self._max:
                self.overflowed += 1
                key = (OVERFLOW_PROGRAM, OVERFLOW_PROGRAM)
                slot = self._stats.get(key)
                if slot is None:
                    slot = self._stats[key] = [0, 0, 0, 0.0]
                return slot
            slot = self._stats[key] = [0, 0, 0, 0.0]
        return slot

    def record_dispatch(
        self,
        program: str,
        route: str = "device",
        live: int = 0,
        padded: int = 0,
        ms: float = 0.0,
    ) -> None:
        route = self._effective_route(route)
        with self._lock:
            slot = self._slot(program, route)
            slot[0] += 1
            slot[1] += int(live)
            slot[2] += int(padded)
            slot[3] += float(ms)

    def add_device_ms(self, program: str, route: str, ms: float) -> None:
        """Attribute block-until-ready time to an already-recorded
        dispatch (the async-pipeline path: queue time and wait time are
        observed at different seams)."""
        route = self._effective_route(route)
        with self._lock:
            self._slot(program, route)[3] += float(ms)

    def record_compile(
        self,
        program: str,
        ms: float,
        site: str = "",
        shared_span: int = 1,
    ) -> None:
        """One XLA compilation: accumulate and append to the JSONL log.

        ``shared_span`` > 1 flags that several programs compiled inside
        ONE timed span (a staged pipeline's first dispatch) — ``ms`` is
        then that span's wall split evenly across them (sums stay equal
        to real wall), not an isolated per-program measurement.
        """
        entry = {
            "program": program,
            "ms": round(float(ms), 3),
            "site": site,
            "ts": time.time(),
        }
        if shared_span > 1:
            entry["shared_span"] = shared_span
        with self._lock:
            acc = self._compiles.get(program)
            if acc is None:
                acc = self._compiles[program] = [0, 0.0]
            acc[0] += 1
            acc[1] += float(ms)
            fh = self._ensure_log()
            if fh is not None:
                try:
                    fh.write(json.dumps(entry) + "\n")
                    fh.flush()
                except OSError:
                    pass  # a full disk must never fault a dispatch seam

    def _ensure_log(self):
        # Caller holds the lock.
        if self.compile_log_path is None:
            return None
        if self._compile_fh is None:
            try:
                self._compile_fh = open(self.compile_log_path, "a")
            except OSError:
                self.compile_log_path = None
                return None
        return self._compile_fh

    def close(self) -> None:
        with self._lock:
            if self._compile_fh is not None:
                try:
                    self._compile_fh.close()
                except OSError:
                    pass
                self._compile_fh = None

    # -- reading --------------------------------------------------------

    def snapshot(self) -> dict:
        """Full ledger state: per-(program, route) dispatch rows (sorted
        by device time, descending) + per-program compile accumulators."""
        with self._lock:
            rows = [
                {
                    "program": program,
                    "route": route,
                    "dispatches": d,
                    "live_lanes": live,
                    "padded_lanes": padded,
                    "device_ms": round(ms, 3),
                    "occupancy": round(live / padded, 4) if padded else None,
                }
                for (program, route), (d, live, padded, ms) in self._stats.items()
            ]
            compiles = {
                program: {"count": c, "ms": round(ms, 3)}
                for program, (c, ms) in self._compiles.items()
            }
            overflowed = self.overflowed
        rows.sort(key=lambda r: (-r["device_ms"], -r["dispatches"]))
        return {
            "dispatches": rows,
            "compiles": compiles,
            "overflowed": overflowed,
        }

    @staticmethod
    def _is_warmup_route(route: str) -> bool:
        return route == "warmup" or route.endswith("/warmup")

    def totals(self, include_warmup: bool = False) -> dict:
        """Whole-process sums (the evidence-line stamp source).

        ``route="warmup"`` rows are excluded by default: warmup lanes are
        all-dead by design (live=0), so folding them in would drag every
        totals-derived occupancy (/statusz, evidence ledger blocks, the
        occupancy gates) toward 0 whenever a warmup ran — exactly the
        pollution the dedicated route exists to prevent.  Compile
        accumulators always count (they are per-program, not per-route).
        """
        with self._lock:
            d = live = padded = 0
            ms = 0.0
            for (_program, route), slot in self._stats.items():
                if not include_warmup and self._is_warmup_route(route):
                    continue
                d += slot[0]
                live += slot[1]
                padded += slot[2]
                ms += slot[3]
            compiles = sum(c for c, _ in self._compiles.values())
            compile_ms = sum(m for _, m in self._compiles.values())
        return {
            "dispatches": d,
            "live_lanes": live,
            "padded_lanes": padded,
            "device_ms": round(ms, 3),
            "compiles": compiles,
            "compile_ms": round(compile_ms, 3),
        }

    def status(self) -> dict:
        """Compact /statusz block: totals + occupancy + the top program
        by attributed device time."""
        t = self.totals()
        t["occupancy"] = (
            round(t["live_lanes"] / t["padded_lanes"], 4)
            if t["padded_lanes"]
            else None
        )
        with self._lock:
            t["programs"] = len(self._stats)
            production = [
                kv
                for kv in self._stats.items()
                if not self._is_warmup_route(kv[0][1])
            ]
            top = max(production, key=lambda kv: kv[1][3], default=None)
        t["top_program"] = (
            {"program": top[0][0], "route": top[0][1], "device_ms": round(top[1][3], 3)}
            if top is not None and top[1][3] > 0
            else None
        )
        return t


# ---------------------------------------------------------------------------
# module-level API (the one-predicate seam surface)
# ---------------------------------------------------------------------------


def enable(
    compile_log: Optional[str] = None,
    max_programs: int = DEFAULT_MAX_PROGRAMS,
) -> CostLedger:
    """Install (and return) a fresh ledger; seams start recording.

    ``compile_log`` names the append-only ``compile_ledger.jsonl`` (None
    keeps compile events in memory only)."""
    global _ledger
    if _ledger is not None:
        _ledger.close()
    _ledger = CostLedger(compile_log=compile_log, max_programs=max_programs)
    return _ledger


def disable() -> None:
    """Remove the ledger; every seam reverts to the no-op path."""
    global _ledger
    if _ledger is not None:
        _ledger.close()
    _ledger = None


def enabled() -> bool:
    return _ledger is not None


def get() -> Optional[CostLedger]:
    return _ledger


def record_dispatch(
    program: str,
    route: str = "device",
    live: int = 0,
    padded: int = 0,
    ms: float = 0.0,
) -> None:
    led = _ledger
    if led is None:
        return
    led.record_dispatch(program, route, live, padded, ms)


def add_device_ms(program: str, route: str, ms: float) -> None:
    led = _ledger
    if led is None:
        return
    led.add_device_ms(program, route, ms)


def record_compile(
    program: str, ms: float, site: str = "", shared_span: int = 1
) -> None:
    led = _ledger
    if led is None:
        return
    led.record_compile(program, ms, site=site, shared_span=shared_span)


def snapshot() -> Optional[dict]:
    led = _ledger
    return led.snapshot() if led is not None else None


def totals() -> Optional[dict]:
    led = _ledger
    return led.totals() if led is not None else None


def status() -> Optional[dict]:
    led = _ledger
    return led.status() if led is not None else None


class _Null:
    """Shared no-op context manager returned while the ledger is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _Null()


class _RouteTag:
    __slots__ = ("_tag", "_tok")

    def __init__(self, tag: str) -> None:
        self._tag = tag
        self._tok = None

    def __enter__(self):
        self._tok = _route_tag.set(self._tag)
        return self

    def __exit__(self, *exc):
        if self._tok is not None:
            _route_tag.reset(self._tok)
        return False


def route_tag(tag: str):
    """Attribute dispatches recorded within this context to ``tag``
    (routes render as ``tag/route``) — the serve plane and the
    aggregation-tree pump wrap their drains so shared seams split out."""
    if _ledger is None:
        return _NULL
    return _RouteTag(tag)


class _CompileWatch:
    """Times a block and records a compile event per watched jit object
    whose program cache grew inside it."""

    __slots__ = ("_led", "_kernels", "_site", "_before", "_t0")

    def __init__(self, led: CostLedger, kernels, site: str) -> None:
        self._led = led
        self._kernels = tuple(kernels)
        self._site = site

    def __enter__(self):
        self._before = [jit_cache_size(fn) for _name, fn in self._kernels]
        self._t0 = time.perf_counter()
        return self

    def _wall_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3

    def _note_compiles(self, wall_ms: float) -> None:
        grew: List[str] = []
        for (name, fn), n0 in zip(self._kernels, self._before):
            if n0 is None:
                continue
            n1 = jit_cache_size(fn)
            if n1 is not None and n1 > n0:
                grew.append(name)
        # k programs compiling inside ONE timed span share its wall: an
        # even split keeps the SUM (totals, /metrics, evidence deltas)
        # equal to the real wall instead of k-times it; shared_span on
        # each JSONL entry flags that the per-program figure is a split,
        # not an isolated measurement.
        for name in grew:
            self._led.record_compile(
                name,
                wall_ms / len(grew),
                site=self._site,
                shared_span=len(grew),
            )

    def __exit__(self, *exc):
        if not exc or exc[0] is None:
            self._note_compiles(self._wall_ms())
        return False


def compile_watch(kernels: Sequence[tuple], site: str = ""):
    """Watch ``((name, jit_obj), ...)`` for compilations inside the block
    (no dispatch record) — the ops-layer entry points use this so kernel
    identity is attributed where the jit objects live."""
    led = _ledger
    if led is None:
        return _NULL
    return _CompileWatch(led, kernels, site)


class _DispatchSpan(_CompileWatch):
    __slots__ = ("_program", "_route", "_live", "_padded", "_mask", "_block")

    def __init__(
        self, led, program, route, live, padded, live_mask, kernels, block, site
    ):
        super().__init__(led, kernels, site or program)
        self._program = program
        self._route = route
        self._live = live
        self._padded = padded
        self._mask = live_mask
        self._block = block

    def __exit__(self, *exc):
        wall_ms = self._wall_ms()
        # A faulted span still counts its dispatch (a launch happened,
        # and the demote-then-retry ladder SHOULD show as extra launches
        # in the gates) but records no compile event: the wall time of a
        # call that died mid-flight measures nothing a compile table can
        # use.
        if not exc or exc[0] is None:
            self._note_compiles(wall_ms)
        live, padded = self._live, self._padded
        if self._mask is not None:
            import numpy as np

            try:
                mask = np.asarray(self._mask)
                live = int(np.count_nonzero(mask))
                padded = int(mask.size)
            except Exception:  # noqa: BLE001 - an abstract tracer (a seam
                # re-jitted by a caller) has no concrete counts; keep the
                # explicit fallbacks rather than faulting the dispatch.
                pass
        self._led.record_dispatch(
            self._program,
            self._route,
            live,
            padded,
            wall_ms if self._block else 0.0,
        )
        return False


def dispatch_span(
    program: str,
    *,
    route: str = "device",
    live: int = 0,
    padded: int = 0,
    live_mask=None,
    kernels: Sequence[tuple] = (),
    block: bool = True,
    site: str = "",
):
    """The seam instrumentation context manager (no-op unless enabled).

    Records one dispatch for ``program`` on ``route`` at exit.  Lane
    occupancy comes from ``live``/``padded`` counts or, when
    ``live_mask`` is given, from the mask array (padded = its size, live
    = its nonzero count — computed only while the ledger is on).
    ``kernels`` are ``(name, jit_obj)`` pairs watched for compilations
    (jit tracing + XLA compilation run synchronously inside the call, so
    a cache that grew inside the span means this span paid the compile
    and its wall time measures it).  ``block=True`` adds the span's wall
    time to the program's device_ms (use when the span covers the
    blocking readback); ``block=False`` records the dispatch without
    timing (async queue seams — the readback seam adds the wait via
    :func:`add_device_ms`).
    """
    led = _ledger
    if led is None:
        return _NULL
    return _DispatchSpan(
        led, program, route, live, padded, live_mask, kernels, block, site
    )
