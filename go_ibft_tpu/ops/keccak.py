"""Batched Keccak-256 on TPU (JAX/XLA), 64-bit lanes as uint32 pairs.

TPUs have no native 64-bit integer lanes in the VPU sweet spot, so the
Keccak-f[1600] state is kept as ``(..., 25, 2)`` uint32 — ``[..., 0]`` the
low half, ``[..., 1]`` the high half of each lane.  All rotation amounts are
static, so a 64-bit rotate is two shifts and an or per half; the 24 rounds
are unrolled into straight-line code and batched by broadcasting.

Two consumers:

* **address derivation** — recovered public keys (limb vectors from
  :mod:`.secp256k1`) are hashed to 20-byte Ethereum-style addresses
  entirely on device, so sender-identity checking
  (reference ``Verifier.IsValidValidator``, core/backend.go:40-44) never
  leaves the chip;
* **payload digests** — ``payload_no_sig`` bytes are packed host-side into
  fixed-bucket padded blocks and absorbed in one ``lax.scan``, one whole
  round's messages per call.

Byte conventions: Keccak absorbs bytes into lanes little-endian.  A
"stream word" here is a uint32 whose LSB is the earliest byte of the byte
stream; digests and addresses are returned as stream words and converted
with the host helpers at the edges.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .fields import LIMB_BITS

__all__ = [
    "RATE_BYTES",
    "keccak_f",
    "keccak256_blocks",
    "limbs_to_words_le",
    "words_le_to_limbs",
    "pubkey_to_address_words",
    "pack_messages",
    "bswap32",
    "digest_words_to_bytes",
    "address_to_words",
    "addresses_to_words",
]

RATE_BYTES = 136  # Keccak-256 rate (17 lanes)

_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

Lane = Tuple[jnp.ndarray, jnp.ndarray]  # (lo, hi) uint32


def _rotl64(lane: Lane, n: int) -> Lane:
    lo, hi = lane
    n %= 64
    if n == 0:
        return lo, hi
    if n >= 32:
        lo, hi = hi, lo
        n -= 32
        if n == 0:
            return lo, hi
    return (
        (lo << n) | (hi >> (32 - n)),
        (hi << n) | (lo >> (32 - n)),
    )


def _xor(a: Lane, b: Lane) -> Lane:
    return a[0] ^ b[0], a[1] ^ b[1]


def _keccak_round(state: jnp.ndarray, rc: jnp.ndarray) -> jnp.ndarray:
    """One Keccak round on a ``(..., 25, 2)`` uint32 state.

    The 24 rounds run under ``lax.scan`` (see :func:`keccak_f`) so this body
    is traced and compiled exactly once — unrolling all rounds produces a
    multi-thousand-op elementwise graph that XLA:CPU compiles pathologically
    slowly.
    """
    a: List[Lane] = [(state[..., i, 0], state[..., i, 1]) for i in range(25)]
    # theta
    c = [
        (
            a[x][0] ^ a[x + 5][0] ^ a[x + 10][0] ^ a[x + 15][0] ^ a[x + 20][0],
            a[x][1] ^ a[x + 5][1] ^ a[x + 10][1] ^ a[x + 15][1] ^ a[x + 20][1],
        )
        for x in range(5)
    ]
    d = [_xor(c[(x - 1) % 5], _rotl64(c[(x + 1) % 5], 1)) for x in range(5)]
    a = [_xor(a[x + 5 * y], d[x]) for y in range(5) for x in range(5)]
    # rho + pi: B[y, 2x+3y] = rotl(A[x, y], r[x][y])
    b: List[Lane] = [None] * 25  # type: ignore[list-item]
    for x in range(5):
        for y in range(5):
            b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl64(a[x + 5 * y], _ROT[x][y])
    # chi
    a = [
        (
            b[x + 5 * y][0] ^ (~b[(x + 1) % 5 + 5 * y][0] & b[(x + 2) % 5 + 5 * y][0]),
            b[x + 5 * y][1] ^ (~b[(x + 1) % 5 + 5 * y][1] & b[(x + 2) % 5 + 5 * y][1]),
        )
        for y in range(5)
        for x in range(5)
    ]
    # iota
    a[0] = (a[0][0] ^ rc[0], a[0][1] ^ rc[1])
    lo = jnp.stack([lane[0] for lane in a], axis=-1)
    hi = jnp.stack([lane[1] for lane in a], axis=-1)
    return jnp.stack([lo, hi], axis=-1)


_RC_WORDS = np.asarray(
    [[rc & 0xFFFFFFFF, rc >> 32] for rc in _RC], dtype=np.uint32
)


def _pallas_mode() -> str:
    """'' (off) | 'compiled' | 'interpret' — the Pallas permutation switch.

    ``GO_IBFT_PALLAS=1`` selects the Pallas kernel on TPU backends (no-op
    elsewhere: the compiled kernel needs Mosaic); ``GO_IBFT_PALLAS=interpret``
    forces the interpreter on any backend (tests/debugging).
    """
    import os

    flag = os.environ.get("GO_IBFT_PALLAS", "")
    if flag == "interpret":
        return "interpret"
    if flag == "1":
        from .pallas_keccak import pallas_supported  # the single predicate

        if pallas_supported():
            return "compiled"
    return ""


def keccak_f(state: jnp.ndarray) -> jnp.ndarray:
    """Keccak-f[1600] on a ``(..., 25, 2)`` uint32 state (scan over rounds).

    With ``GO_IBFT_PALLAS`` set (see :func:`_pallas_mode`), 1-D batches
    route to the Pallas kernel's register-native layout instead
    (:mod:`.pallas_keccak`).
    """
    mode = _pallas_mode()
    if mode and state.ndim == 3 and state.shape[-2:] == (25, 2):
        from .pallas_keccak import keccak_f_pallas  # lazy: avoids a cycle

        return keccak_f_pallas(state, interpret=mode == "interpret")

    def body(st, rc):
        return _keccak_round(st, rc), None

    out, _ = jax.lax.scan(body, state, jnp.asarray(_RC_WORDS))
    return out


def keccak256_blocks(
    blocks: jnp.ndarray, num_blocks: jnp.ndarray
) -> jnp.ndarray:
    """Digest pre-padded rate blocks; returns ``(..., 8)`` uint32 stream words.

    ``blocks`` is ``(..., B, 17, 2)`` uint32 (17 lanes per 136-byte rate
    block, already multi-rate padded by :func:`pack_messages`);
    ``num_blocks`` is ``(...,)`` int32 in ``[1, B]``.  Blocks past
    ``num_blocks`` are ignored via a select, so one compiled program serves
    any message length up to the bucket.
    """
    bmax = blocks.shape[-3]
    batch = blocks.shape[:-3]
    state = jnp.zeros(batch + (25, 2), dtype=jnp.uint32)

    xs = jnp.moveaxis(blocks, -3, 0)  # (B, ..., 17, 2)

    def body(state, inp):
        i, blk = inp
        absorbed = state.at[..., :17, :].set(state[..., :17, :] ^ blk)
        nxt = keccak_f(absorbed)
        live = (i < num_blocks)[..., None, None]
        return jnp.where(live, nxt, state), None

    state, _ = jax.lax.scan(body, state, (jnp.arange(bmax), xs))
    # Digest = first 4 lanes, little-endian => stream words interleave lo/hi.
    out = state[..., :4, :]  # (..., 4, 2)
    return out.reshape(batch + (8,))


def bswap32(w: jnp.ndarray) -> jnp.ndarray:
    """Byte-swap each uint32 (big-endian <-> little-endian words)."""
    return (
        (w >> 24)
        | ((w >> 8) & jnp.uint32(0xFF00))
        | ((w << 8) & jnp.uint32(0xFF0000))
        | (w << 24)
    )


def limbs_to_words_le(limbs: jnp.ndarray, nwords: int = 8) -> jnp.ndarray:
    """Canonical 13-bit limbs -> little-endian uint32 words of the integer."""
    u = limbs.astype(jnp.uint32)
    words = []
    nl = limbs.shape[-1]
    for j in range(nwords):
        acc = jnp.zeros(limbs.shape[:-1], dtype=jnp.uint32)
        for k in range(nl):
            lo_bit = LIMB_BITS * k
            if lo_bit + LIMB_BITS <= 32 * j or lo_bit >= 32 * (j + 1):
                continue
            sh = lo_bit - 32 * j
            if sh >= 0:
                acc = acc | (u[..., k] << sh)  # uint32 << wraps = truncation
            else:
                acc = acc | (u[..., k] >> (-sh))
        words.append(acc)
    return jnp.stack(words, axis=-1)


def words_le_to_limbs(words: jnp.ndarray, nlimbs: int) -> jnp.ndarray:
    """Little-endian uint32 words -> canonical 13-bit int32 limbs."""
    limbs = []
    nw = words.shape[-1]
    mask = jnp.uint32((1 << LIMB_BITS) - 1)
    for k in range(nlimbs):
        lo_bit = LIMB_BITS * k
        j = lo_bit // 32
        sh = lo_bit - 32 * j
        acc = jnp.zeros(words.shape[:-1], dtype=jnp.uint32)
        if j < nw:
            acc = words[..., j] >> sh
            if sh + LIMB_BITS > 32 and j + 1 < nw:
                acc = acc | (words[..., j + 1] << (32 - sh))
        limbs.append((acc & mask).astype(jnp.int32))
    return jnp.stack(limbs, axis=-1)


def pubkey_to_address_words(
    qx_limbs: jnp.ndarray, qy_limbs: jnp.ndarray
) -> jnp.ndarray:
    """keccak256(X32 || Y32)[12:] on device; ``(..., 5)`` uint32 stream words.

    Input limbs must be canonical (:func:`go_ibft_tpu.ops.fields.canon`).
    Matches :func:`go_ibft_tpu.crypto.ecdsa.pubkey_to_address` byte-for-byte.
    """
    xw = limbs_to_words_le(qx_limbs)  # value words, little-endian
    yw = limbs_to_words_le(qy_limbs)
    # Big-endian serialization: stream word j of X = bswap(value word 7-j).
    stream = [bswap32(xw[..., 7 - j]) for j in range(8)]
    stream += [bswap32(yw[..., 7 - j]) for j in range(8)]
    batch = qx_limbs.shape[:-1]
    # One 64-byte message in a single 136-byte rate block, padded.
    lanes = jnp.zeros(batch + (17, 2), dtype=jnp.uint32)
    for t in range(8):
        lanes = lanes.at[..., t, 0].set(stream[2 * t])
        lanes = lanes.at[..., t, 1].set(stream[2 * t + 1])
    # padding: byte 64 = 0x01 (lane 8 lo byte 0), byte 135 = 0x80 (lane 16 hi
    # byte 3, i.e. top byte)
    lanes = lanes.at[..., 8, 0].set(jnp.uint32(0x01))
    lanes = lanes.at[..., 16, 1].set(jnp.uint32(0x80) << 24)
    digest = keccak256_blocks(
        lanes[..., None, :, :], jnp.ones(batch, dtype=jnp.int32)
    )  # (..., 8) stream words
    # Address = digest bytes 12..31 = stream words 3..7
    return digest[..., 3:]


# ---------------------------------------------------------------------------
# Host-side packing helpers (numpy, run once per batch at the edge)
# ---------------------------------------------------------------------------


def pack_messages(
    payloads: Sequence[bytes], max_blocks: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad byte strings to Keccak rate blocks as uint32 lane pairs.

    Returns ``(blocks, num_blocks)`` with ``blocks`` of shape
    ``(N, max_blocks, 17, 2)`` uint32 and ``num_blocks`` int32.  Raises if a
    payload exceeds the bucket (callers choose buckets; see
    ``verify.bucketing``).

    Vectorized: one flat ``(N, max_blocks * RATE)`` byte staging buffer, one
    row-memcpy per payload (a single C-level join when all payloads share a
    length — the common same-shape-envelopes case), and the multi-rate
    padding applied as two fancy-indexed XORs — ``b ^ 0x01`` at the payload
    end, ``b ^ 0x80`` at the block end, coinciding to ``0x81`` when the pad
    is one byte.  Bit-identical to :func:`_pack_messages_reference` (pinned
    by tests/test_pack_vectorized.py).
    """
    n = len(payloads)
    if n == 0:
        return (
            np.zeros((0, max_blocks, 17, 2), dtype=np.uint32),
            np.zeros((0,), dtype=np.int32),
        )
    lens = np.fromiter((len(p) for p in payloads), dtype=np.int64, count=n)
    nbs = lens // RATE_BYTES + 1  # padding always adds [1, RATE] bytes
    if (nbs > max_blocks).any():
        i = int(np.argmax(nbs))
        raise ValueError(
            f"payload of {int(lens[i])} bytes needs {int(nbs[i])} blocks "
            f"> bucket {max_blocks}"
        )
    buf = np.zeros((n, max_blocks * RATE_BYTES), dtype=np.uint8)
    width = int(lens[0])
    if width and (lens == width).all():
        flat = np.frombuffer(b"".join(payloads), dtype=np.uint8)
        buf[:, :width] = flat.reshape(n, width)
    else:
        for i, data in enumerate(payloads):
            if data:
                buf[i, : len(data)] = np.frombuffer(data, dtype=np.uint8)
    rows = np.arange(n)
    buf[rows, lens] ^= 0x01
    buf[rows, nbs * RATE_BYTES - 1] ^= 0x80
    lanes = buf.view("<u4").reshape(n, max_blocks, 34)
    blocks = np.empty((n, max_blocks, 17, 2), dtype=np.uint32)
    blocks[..., 0] = lanes[:, :, 0::2]
    blocks[..., 1] = lanes[:, :, 1::2]
    return blocks, nbs.astype(np.int32)


def _pack_messages_reference(
    payloads: Sequence[bytes], max_blocks: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-message loop packer — the parity oracle for :func:`pack_messages`.

    Kept verbatim (one bytearray + frombuffer per message) so the vectorized
    path has a bit-identity reference to diff against; not a hot path.
    """
    n = len(payloads)
    blocks = np.zeros((n, max_blocks, 17, 2), dtype=np.uint32)
    counts = np.zeros((n,), dtype=np.int32)
    for i, data in enumerate(payloads):
        padded = bytearray(data)
        pad_len = RATE_BYTES - (len(padded) % RATE_BYTES)
        if pad_len == 1:
            padded += b"\x81"
        else:
            padded += b"\x01" + b"\x00" * (pad_len - 2) + b"\x80"
        nb = len(padded) // RATE_BYTES
        if nb > max_blocks:
            raise ValueError(
                f"payload of {len(data)} bytes needs {nb} blocks > bucket {max_blocks}"
            )
        counts[i] = nb
        arr = np.frombuffer(bytes(padded), dtype="<u4").reshape(nb, 34)
        blocks[i, :nb, :, 0] = arr[:, 0::2]
        blocks[i, :nb, :, 1] = arr[:, 1::2]
    return blocks, counts


def digest_words_to_bytes(words: np.ndarray) -> bytes:
    """``(8,)`` uint32 stream words -> 32 digest bytes."""
    return np.asarray(words, dtype="<u4").tobytes()


def address_to_words(address: bytes) -> np.ndarray:
    """20-byte address -> ``(5,)`` uint32 stream words."""
    if len(address) != 20:
        raise ValueError("address must be 20 bytes")
    return np.frombuffer(address, dtype="<u4").copy()


def addresses_to_words(addresses: Sequence[bytes]) -> np.ndarray:
    """Bulk :func:`address_to_words`: ``N`` addresses -> ``(N, 5)`` uint32.

    One C-level join + one frombuffer instead of N per-address calls; raises
    on any address that is not exactly 20 bytes (same contract as the
    scalar helper, checked up front so the error names the offending lane).
    """
    for i, a in enumerate(addresses):
        if len(a) != 20:
            raise ValueError(f"address {i} must be 20 bytes, got {len(a)}")
    n = len(addresses)
    if n == 0:
        return np.zeros((0, 5), dtype=np.uint32)
    return (
        np.frombuffer(b"".join(addresses), dtype="<u4").reshape(n, 5).copy()
    )
