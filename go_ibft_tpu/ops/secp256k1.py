"""secp256k1 curve arithmetic and batched ECDSA on TPU (JAX/XLA).

This is the data plane behind the reference's ``Verifier`` predicates
(go-ibft core/backend.go:37-56): where the reference calls
``IsValidValidator`` / ``IsValidCommittedSeal`` once per message under the
store lock (messages/messages.go:183-198), this module verifies or recovers
a whole round's signatures in one ``jit``-compiled, fixed-shape batch.

Design notes (TPU-first, not a port — the reference has no crypto at all):

* Field elements are radix-2**13 limb vectors (:mod:`.fields`), batched by
  broadcasting over leading axes; every op here is shape-static and
  branch-free so ``vmap``/``jit`` see one straight-line program.
* Points are Jacobian ``(X, Y, Z)`` with infinity encoded as ``Z == 0`` —
  exceptional cases (infinity operands, P == Q, P == -Q) are resolved with
  branchless selects, never Python control flow.
* Double-scalar multiplication ``k1*G + k2*Q`` uses Shamir's trick inside a
  single ``lax.scan`` of 256 fixed steps, so ECDSA verify and recovery cost
  one interleaved ladder instead of two.
* All public entry points accept/return limb arrays; host packing helpers
  live in :mod:`go_ibft_tpu.crypto`.

Curve: y**2 = x**3 + 7 over GF(P), group order N (both primes close under
2**256, so the pseudo-Mersenne folding path of :mod:`.fields` applies).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import fields
from .fields import LIMB_BITS, LIMB_MASK, Modulus

__all__ = [
    "P",
    "N",
    "GX",
    "GY",
    "FIELD",
    "ORDER",
    "JacobianPoint",
    "point_infinity",
    "point_double",
    "point_add",
    "to_affine",
    "is_infinity",
    "on_curve",
    "ecmul2_base",
    "glv_split",
    "ecdsa_verify",
    "ecdsa_recover",
]

# Curve constants (SEC 2 v2, "Recommended Parameters secp256k1").
P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

FIELD = Modulus(P)
ORDER = Modulus(N)
_L = FIELD.nlimbs  # == ORDER.nlimbs == 20


class JacobianPoint(NamedTuple):
    """Batched Jacobian point; each coordinate is an ``(..., 20)`` limb array."""

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray


def point_infinity(batch_shape: Tuple[int, ...] = ()) -> JacobianPoint:
    one = jnp.broadcast_to(jnp.asarray(FIELD.const(1)), batch_shape + (_L,))
    zero = jnp.zeros(batch_shape + (_L,), dtype=jnp.int32)
    return JacobianPoint(one, one, zero)


def is_infinity(p: JacobianPoint) -> jnp.ndarray:
    return fields.is_zero_fast(FIELD, p.z)


def _sel_pt(cond: jnp.ndarray, a: JacobianPoint, b: JacobianPoint) -> JacobianPoint:
    return JacobianPoint(
        fields.select(cond, a.x, b.x),
        fields.select(cond, a.y, b.y),
        fields.select(cond, a.z, b.z),
    )


@jax.jit
def point_double(p: JacobianPoint) -> JacobianPoint:
    """Jacobian doubling, a = 0 case ("dbl-2009-l" shape).

    Safe for infinity (Z=0 stays Z=0); secp256k1 has no 2-torsion so Y=0
    never occurs on-curve.
    """
    f = FIELD
    a = fields.sqr(f, p.x)
    b = fields.sqr(f, p.y)
    c = fields.sqr(f, b)
    # D = 2*((X+B)^2 - A - C)
    t = fields.sqr(f, fields.add(f, p.x, b))
    d = fields.muli(f, fields.sub(f, fields.sub(f, t, a), c), 2)
    e = fields.muli(f, a, 3)
    ff = fields.sqr(f, e)
    x3 = fields.sub(f, ff, fields.muli(f, d, 2))
    y3 = fields.sub(f, fields.mul(f, e, fields.sub(f, d, x3)), fields.muli(f, c, 8))
    z3 = fields.muli(f, fields.mul(f, p.y, p.z), 2)
    return JacobianPoint(x3, y3, z3)


@jax.jit
def point_add(p: JacobianPoint, q: JacobianPoint) -> JacobianPoint:
    """Complete Jacobian addition via branchless selects.

    Handles all exceptional cases: either operand at infinity, P == Q
    (falls back to doubling), and P == -Q (returns infinity, which the
    generic formula produces naturally since H == 0, R != 0 => Z3 == 0).
    """
    f = FIELD
    z1s = fields.sqr(f, p.z)
    z2s = fields.sqr(f, q.z)
    u1 = fields.mul(f, p.x, z2s)
    u2 = fields.mul(f, q.x, z1s)
    s1 = fields.mul(f, p.y, fields.mul(f, z2s, q.z))
    s2 = fields.mul(f, q.y, fields.mul(f, z1s, p.z))
    h = fields.sub(f, u2, u1)
    r = fields.sub(f, s2, s1)
    hs = fields.sqr(f, h)
    hc = fields.mul(f, hs, h)
    u1hs = fields.mul(f, u1, hs)
    x3 = fields.sub(f, fields.sub(f, fields.sqr(f, r), hc), fields.muli(f, u1hs, 2))
    y3 = fields.sub(
        f, fields.mul(f, r, fields.sub(f, u1hs, x3)), fields.mul(f, s1, hc)
    )
    z3 = fields.mul(f, fields.mul(f, p.z, q.z), h)
    generic = JacobianPoint(x3, y3, z3)

    same_x = fields.is_zero_fast(f, h)
    same_y = fields.is_zero_fast(f, r)
    out = _sel_pt(same_x & same_y, point_double(p), generic)
    out = _sel_pt(is_infinity(p), q, out)
    out = _sel_pt(is_infinity(q), p, out)
    return out


def _inv_lanes(m: fields.Modulus, a: jnp.ndarray) -> jnp.ndarray:
    """Inverse, amortized across a single batch axis when one exists.

    A ``(B, L)`` input takes the Montgomery product tree (ONE Fermat scan
    for the whole batch); any other shape falls back to per-lane Fermat.
    Trace-time decision — shapes are static under jit."""
    if a.ndim == 2 and a.shape[0] >= 2:
        return fields.batch_inv(m, a)
    return fields.inv(m, a)


@jax.jit
def to_affine(p: JacobianPoint) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Canonical affine ``(x, y)``; infinity maps to ``(0, 0)``."""
    f = FIELD
    zinv = _inv_lanes(f, p.z)  # inv(0) == 0, so infinity folds to (0, 0)
    zi2 = fields.sqr(f, zinv)
    x = fields.mul(f, p.x, zi2)
    y = fields.mul(f, p.y, fields.mul(f, zi2, zinv))
    return fields.canon(f, x), fields.canon(f, y)


@jax.jit
def on_curve(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Affine on-curve test: y^2 == x^3 + 7 (mod P)."""
    f = FIELD
    lhs = fields.sqr(f, y)
    rhs = fields.add(
        f, fields.mul(f, fields.sqr(f, x), x), jnp.asarray(f.const(7))
    )
    return fields.eq_mod(f, lhs, rhs)


def point_add_mixed(
    p: JacobianPoint, qx: jnp.ndarray, qy: jnp.ndarray
) -> JacobianPoint:
    """Complete mixed addition (affine addend, Z2 == 1): ~11 field muls.

    Exceptional cases handled branchlessly: P at infinity -> Q; P == Q ->
    doubling; P == -Q -> infinity (Z3 == 0 falls out of the formula)."""
    f = FIELD
    z1s = fields.sqr(f, p.z)
    u2 = fields.mul(f, qx, z1s)
    s2 = fields.mul(f, qy, fields.mul(f, z1s, p.z))
    h = fields.sub(f, u2, p.x)
    r = fields.sub(f, s2, p.y)
    hs = fields.sqr(f, h)
    hc = fields.mul(f, hs, h)
    u1hs = fields.mul(f, p.x, hs)
    x3 = fields.sub(f, fields.sub(f, fields.sqr(f, r), hc), fields.muli(f, u1hs, 2))
    y3 = fields.sub(
        f, fields.mul(f, r, fields.sub(f, u1hs, x3)), fields.mul(f, p.y, hc)
    )
    z3 = fields.mul(f, p.z, h)
    generic = JacobianPoint(x3, y3, z3)

    same_x = fields.is_zero_fast(f, h)
    same_y = fields.is_zero_fast(f, r)
    out = _sel_pt(same_x & same_y, point_double(p), generic)
    one = jnp.broadcast_to(jnp.asarray(f.const(1)), p.z.shape)
    return _sel_pt(is_infinity(p), JacobianPoint(qx, qy, one), out)


_WINDOW = 4
_NWIN = 64  # 256 / 4


def _double4(p: JacobianPoint) -> JacobianPoint:
    """Four successive doublings as a length-4 inner scan.

    Four INLINE ``point_double`` calls instantiate the doubling body four
    times inside the ladder's scan body — ~7k stablehlo lines that are pure
    compile time (the XLA:CPU certify budget tracks trace size,
    docs/PERFORMANCE.md).  A fixed-length inner scan traces it ONCE; the
    cost is 3 extra While iterations per ladder window (~100 per recover),
    noise against the ~40 field muls each iteration already runs.
    """
    return jax.lax.scan(lambda q, _: (point_double(q), None), p, None, length=4)[0]

# ---------------------------------------------------------------------------
# GLV endomorphism (secp256k1 has CM discriminant -3): phi(x, y) = (BETA*x, y)
# acts as scalar multiplication by LAMBDA, where BETA**3 == 1 (mod P) and
# LAMBDA**3 == 1 (mod N).  Splitting a scalar k = k1 + k2*LAMBDA with
# |k1|, |k2| < 2**129 halves the ladder length: 33 four-bit windows over
# FOUR half-length digit streams (G, phi(G), Q, phi(Q)) instead of 64
# windows over two full-length ones — 132 shared doublings instead of 256.
# Constants derived via extended Euclid on (N, LAMBDA) (GLV method; see
# /tmp-free derivation in tests/test_secp256k1.py::test_glv_constants).
# ---------------------------------------------------------------------------
_LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
_BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE
# Short lattice basis v1 = (A1, B1), v2 = (A2, B2) of
# {(x, y) : x + y*LAMBDA === 0 (mod N)}.
_GLV_A1 = 0x3086D221A7D46BCDE86C90E49284EB15
_GLV_B1 = -0xE4437ED6010E88286F547FA90ABFE4C3
_GLV_A2 = 0x114CA50F7A8E2F3F657C1108D9D44CFD8
_GLV_B2 = 0x3086D221A7D46BCDE86C90E49284EB15
# Rounding multipliers: round(k * Gi / 2**384) == round(k * |b_i| / N)
# exactly for all k < N (384 bits of precision leave the rounded quotient
# off by at most 1, which the +-2**129 half-scalar bound absorbs; validated
# exhaustively against the exact-rational formula in tests).
_GLV_SHIFT = 384
_GLV_G1 = (_GLV_B2 * (1 << _GLV_SHIFT) + N // 2) // N
_GLV_G2 = (-_GLV_B1 * (1 << _GLV_SHIFT) + N // 2) // N

assert pow(_LAMBDA, 3, N) == 1 and _LAMBDA != 1
assert pow(_BETA, 3, P) == 1 and _BETA != 1
assert (_GLV_A1 + _GLV_B1 * _LAMBDA) % N == 0
assert (_GLV_A2 + _GLV_B2 * _LAMBDA) % N == 0

_GLV_HL = 11  # half-scalar limb count: 143 bits >= 129-bit magnitude + sign
_GLV_NWIN = 33  # 4-bit windows covering 132 bits
_GLV_G1_L = fields.to_limbs([_GLV_G1], _L)[0]
_GLV_G2_L = fields.to_limbs([_GLV_G2], _L)[0]
_GLV_A1_L = fields.to_limbs([_GLV_A1], _GLV_HL)[0]
_GLV_A2_L = fields.to_limbs([_GLV_A2], _GLV_HL)[0]
_GLV_NB1_L = fields.to_limbs([-_GLV_B1], _GLV_HL)[0]
_GLV_B2_L = fields.to_limbs([_GLV_B2], _GLV_HL)[0]
# k*G fits 512 bits; + the 2**383 rounding addend stays under 13*41 bits.
_GLV_PROD_LEN = 41
_GLV_ROUND = np.zeros(_GLV_PROD_LEN, dtype=np.int32)
_GLV_ROUND[_GLV_SHIFT // LIMB_BITS] = 1 << (_GLV_SHIFT % LIMB_BITS - 1)


def _glv_round_shift(k: jnp.ndarray, g_limbs: np.ndarray) -> jnp.ndarray:
    """``round((k * g) / 2**384)`` exactly, as an ``(..., 11)`` limb vector.

    ``k`` canonical ``(..., 20)``; ``g`` a static 256-bit constant.  The
    full 533-bit product is normalized (lazy carries + Kogge-Stone exact
    pass — no sequential limb scan), then bits >= 384 are re-packed into
    13-bit limbs."""
    z = fields._conv(k, jnp.asarray(g_limbs), _GLV_PROD_LEN)
    z = z + jnp.asarray(_GLV_ROUND)
    z = fields._carry(z, 4)
    z = fields._ks_carry(z)
    base = _GLV_SHIFT // LIMB_BITS  # 29, shift-within-limb 7
    lo = z[..., base : base + _GLV_HL] >> 7
    hi = (z[..., base + 1 : base + 1 + _GLV_HL] << 6) & LIMB_MASK
    return lo | hi


def _q_window_table(
    batch: Tuple[int, ...], qx: jnp.ndarray, qy: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-batch window table ``T[d] = d*Q`` (Jacobian; T[0] = infinity),
    stacked as ``(16, ..., L)`` coordinate arrays.

    Built with a 14-step ``lax.scan`` rather than 14 unrolled mixed adds:
    each unrolled add is ~2*10^3 HLO ops and the table sits inside the
    repo's largest fused programs — on XLA:CPU trace size IS compile time
    (an unrolled table pushed the fused certify compile past 25 minutes).
    """
    one = jnp.asarray(FIELD.const(1))
    q_pt = JacobianPoint(qx, qy, jnp.broadcast_to(one, batch + (_L,)))
    inf = point_infinity(batch)

    def tab_body(prev, _):
        nxt = point_add_mixed(prev, qx, qy)
        return nxt, nxt

    _, tail = jax.lax.scan(tab_body, q_pt, None, length=14)  # 2Q .. 15Q
    qtx = jnp.concatenate([inf.x[None], q_pt.x[None], tail.x])
    qty = jnp.concatenate([inf.y[None], q_pt.y[None], tail.y])
    qtz = jnp.concatenate([inf.z[None], q_pt.z[None], tail.z])
    return qtx, qty, qtz


def _conv_lo(a: jnp.ndarray, b: np.ndarray, n: int) -> jnp.ndarray:
    """Low ``n`` limb-columns of the schoolbook product (mod-2**(13n)).

    The GLV signed combinations only need the value mod 2**143; columns
    >= n fall off :func:`fields._conv`'s truncating slice (every retained
    column sum stays < 2**31 in int32)."""
    return fields._conv(a, jnp.asarray(b), n)


def _glv_neg143(r: jnp.ndarray) -> jnp.ndarray:
    """``2**143 - r`` for ``0 < r < 2**143`` in 11 canonical limbs."""
    flipped = LIMB_MASK - r
    flipped = flipped.at[..., 0].add(1)
    return fields._exact_carry(flipped)


def glv_split(
    k: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Decompose canonical ``k < N`` into ``k === s1*|k1| + s2*|k2|*LAMBDA``.

    Returns ``(abs1, neg1, abs2, neg2)``: magnitudes as ``(..., 11)`` limb
    vectors < 2**129 and sign flags (True = negative).  All arithmetic is
    exact: the signed combinations are evaluated mod 2**143 in int32 limb
    space (conv columns stay < 2**31) and the sign read off bit 142.
    """
    c1 = _glv_round_shift(k, _GLV_G1_L)
    c2 = _glv_round_shift(k, _GLV_G2_L)

    def signed(parts):
        s = parts[0]
        for term in parts[1:]:
            s = s + term
        r = fields._exact_carry(s)  # >> and & floor correctly on negatives
        neg = (r[..., _GLV_HL - 1] >> 12) == 1
        return fields.select(neg, _glv_neg143(r), r), neg

    t1 = _conv_lo(c1, _GLV_A1_L, _GLV_HL)
    t2 = _conv_lo(c2, _GLV_A2_L, _GLV_HL)
    abs1, neg1 = signed([k[..., :_GLV_HL], -t1, -t2])  # k - c1*a1 - c2*a2
    u1 = _conv_lo(c1, _GLV_NB1_L, _GLV_HL)
    u2 = _conv_lo(c2, _GLV_B2_L, _GLV_HL)
    abs2, neg2 = signed([u1, -u2])  # -c1*b1 - c2*b2
    return abs1, neg1, abs2, neg2


def _precompute_g_table() -> Tuple[np.ndarray, np.ndarray]:
    """Fixed-base window table: entry [d] = d * G, affine, d in 1..15.

    Computed once at import with host integer arithmetic; the table is tiny
    ((16, 20) int32 x 2) and closes over the jit as a constant.  The
    ladder's four shared doublings per scan step supply the ``16**j``
    scaling for BOTH scalar terms, so the G entries must NOT be pre-scaled
    by ``16**j`` — a pre-scaled comb riding the same ladder would scale the
    G term by ``16**j`` twice (regression: ``ecmul2_base(16, 0, G)`` must
    equal ``16*G``, not ``256*G``).
    """
    from ..crypto import ecdsa as _host

    from .fields import to_limbs

    gx_tab = np.zeros((16, _L), dtype=np.int32)
    gy_tab = np.zeros((16, _L), dtype=np.int32)
    pt = None
    for d in range(1, 16):
        pt = _host._add(pt, (GX, GY))
        gx_tab[d] = to_limbs([pt[0]], _L)[0]
        gy_tab[d] = to_limbs([pt[1]], _L)[0]
    return gx_tab, gy_tab


_G_TAB_X, _G_TAB_Y = _precompute_g_table()


def _precompute_glv_g_table() -> np.ndarray:
    """GLV companion to the fixed-base window table.

    ``phi`` maps affine ``(x, y)`` to ``(BETA*x, y)`` and commutes with
    scalar multiplication, so the ``d*phi(G)`` table is the ``d*G`` table
    with x scaled by BETA (shared y; sign flips happen at gather time in
    the ladder, so no negated table is stored).
    """
    from .fields import from_limbs, to_limbs

    gpx = np.zeros((16, _L), dtype=np.int32)
    xs = from_limbs(_G_TAB_X)
    for d in range(1, 16):
        gpx[d] = to_limbs([(_BETA * xs[d]) % P], _L)[0]
    return gpx


_GP_TAB_X = _precompute_glv_g_table()

# Static nibble-extraction indices: bit position 4j may straddle a 13-bit
# limb boundary; precompute (limb, shift, need-hi) per window.
_NIB_POS = np.arange(_NWIN - 1, -1, -1) * _WINDOW  # MSB-first
_NIB_LIMB = _NIB_POS // LIMB_BITS
_NIB_OFF = _NIB_POS % LIMB_BITS
_NIB_HI = np.minimum(_NIB_LIMB + 1, 19)
_NIB_NEEDHI = (_NIB_OFF > LIMB_BITS - _WINDOW).astype(np.int32)


def _scalar_nibbles_msb(k: jnp.ndarray) -> jnp.ndarray:
    """4-bit windows of canonical scalar ``k``, MSB first: ``(64,) + batch``."""
    lo = jnp.take(k, jnp.asarray(_NIB_LIMB), axis=-1) >> jnp.asarray(
        _NIB_OFF.astype(np.int32)
    )
    hi = jnp.take(k, jnp.asarray(_NIB_HI), axis=-1) << jnp.asarray(
        (LIMB_BITS - _NIB_OFF).astype(np.int32)
    )
    nib = (lo | hi * jnp.asarray(_NIB_NEEDHI)) & 0xF
    return jnp.moveaxis(nib, -1, 0)


# Branchless 16-way gather (4-level where tree, NOT a one-hot einsum — an
# int32 dot_general per scan step lowers poorly on TPU; measured r03,
# scripts/ab_ladder_select.py).  Now shared with fields.pow_fixed.
_one_hot_select = fields.select16

# Static nibble tables for GLV half-scalars: 33 MSB-first 4-bit windows of
# an 11-limb (143-bit) magnitude (bits 132..142 are provably zero).
_GNIB_POS = np.arange(_GLV_NWIN - 1, -1, -1) * _WINDOW
_GNIB_LIMB = _GNIB_POS // LIMB_BITS
_GNIB_OFF = _GNIB_POS % LIMB_BITS
_GNIB_HI = np.minimum(_GNIB_LIMB + 1, _GLV_HL - 1)
_GNIB_NEEDHI = (_GNIB_OFF > LIMB_BITS - _WINDOW).astype(np.int32)


def _glv_nibbles_msb(k: jnp.ndarray) -> jnp.ndarray:
    """4-bit windows of an 11-limb magnitude, MSB first: ``(33,) + batch``."""
    lo = jnp.take(k, jnp.asarray(_GNIB_LIMB), axis=-1) >> jnp.asarray(
        _GNIB_OFF.astype(np.int32)
    )
    hi = jnp.take(k, jnp.asarray(_GNIB_HI), axis=-1) << jnp.asarray(
        (LIMB_BITS - _GNIB_OFF).astype(np.int32)
    )
    nib = (lo | hi * jnp.asarray(_GNIB_NEEDHI)) & 0xF
    return jnp.moveaxis(nib, -1, 0)


@jax.jit
def _ecmul2_base_shamir(
    k1: jnp.ndarray, k2: jnp.ndarray, qx: jnp.ndarray, qy: jnp.ndarray
) -> JacobianPoint:
    """Pre-GLV double-scalar multiply: ``k1*G + k2*Q`` (Shamir/Straus).

    4-bit interleaved windows over a 64-step ``lax.scan``: 4 shared
    doublings per step, one *mixed* add from the precomputed fixed-base
    ``d*G`` window table (the shared doublings supply the ``16**j``
    scaling), and one Jacobian add from the per-batch 16-entry Q table.

    Kept as the A/B baseline for :func:`ecmul2_base` (the GLV ladder) and
    as an independent oracle in the parity tests — it shares no
    decomposition code with the GLV path.
    """
    batch = jnp.broadcast_shapes(k1.shape[:-1], k2.shape[:-1], qx.shape[:-1])
    qx = jnp.broadcast_to(qx, batch + (_L,))
    qy = jnp.broadcast_to(qy, batch + (_L,))
    qtx, qty, qtz = _q_window_table(batch, qx, qy)  # (16, ..., L)

    n1 = jnp.broadcast_to(
        _scalar_nibbles_msb(fields.canon(ORDER, k1)), (_NWIN,) + batch
    )
    n2 = jnp.broadcast_to(
        _scalar_nibbles_msb(fields.canon(ORDER, k2)), (_NWIN,) + batch
    )

    g_tab_x = jnp.asarray(_G_TAB_X)  # (16, L) d*G entries, constant
    g_tab_y = jnp.asarray(_G_TAB_Y)

    def body(acc, inp):
        d1, d2 = inp
        # 4 shared doublings (doubling infinity is safe: Z stays 0)
        acc = _double4(acc)
        # G term: mixed add of d1*G from the fixed window table (skip when
        # digit == 0)
        with_g = point_add_mixed(
            acc, _one_hot_select(d1, g_tab_x), _one_hot_select(d1, g_tab_y)
        )
        acc = _sel_pt(d1 == 0, acc, with_g)
        # Q term: full Jacobian add from the per-batch table (T[0] = inf is
        # handled by point_add's completeness)
        addq = JacobianPoint(
            _one_hot_select(d2, qtx), _one_hot_select(d2, qty), _one_hot_select(d2, qtz)
        )
        acc = point_add(acc, addq)
        return acc, None

    acc, _ = jax.lax.scan(body, point_infinity(batch), (n1, n2))
    return acc


@jax.jit
def ecmul2_base(
    k1: jnp.ndarray, k2: jnp.ndarray, qx: jnp.ndarray, qy: jnp.ndarray
) -> JacobianPoint:
    """GLV double-scalar multiply: ``k1*G + k2*Q`` in a 33-step ladder.

    Both scalars are lambda-split (:func:`glv_split`) into signed
    half-scalars, giving FOUR 4-bit digit streams over 129-bit magnitudes:
    ``k1*G = s11*|a|*G + s12*|b|*phi(G)`` and likewise for ``Q`` (phi(Q)
    table entries reuse the Q table with x scaled by BETA — phi commutes
    with scalar multiplication).  Signs are applied at gather time by
    negating y, so tables are built once.

    Accumulation is the TPU-shaped variant of Straus interleaving: the
    four digit streams accumulate into four INDEPENDENT lanes of one
    ``(4,) + batch`` Jacobian point (``acc_i = sum_j 16**j * T_i[d_ij]``
    — doublings distribute over the final sum), combined by two batched
    adds after the scan.  A scan step is therefore 4 batched doublings +
    ONE batched complete add over the stacked ``(16, 4, ...)`` tables —
    the per-step sequential chain drops from 8 point ops (4 dbl + 4
    serial adds, the r04 shape) to 5, and the traced body roughly halves,
    which is compile time on XLA:CPU (VERDICT r04 weak #3).  Table entry
    0 is the point at infinity, so zero digits need no select — complete
    addition absorbs them.  Net: 132 sequential doublings instead of the
    Shamir ladder's 256 (this is the hottest loop of the framework — the
    per-message ``Verifier`` work of reference messages/messages.go:183-198
    rides entirely on it).

    ``k1``/``k2`` are semi-reduced scalars mod N; ``qx``/``qy`` affine
    field elements.
    """
    batch = jnp.broadcast_shapes(k1.shape[:-1], k2.shape[:-1], qx.shape[:-1])
    qx = jnp.broadcast_to(qx, batch + (_L,))
    qy = jnp.broadcast_to(qy, batch + (_L,))
    qtx, qty, qtz = _q_window_table(batch, qx, qy)  # (16, ..., L)
    # phi(Q) table: x scaled by BETA across the table axis (one batched mul).
    qptx = fields.mul(FIELD, qtx, jnp.asarray(FIELD.const(_BETA)))

    a1, s1, a2, s2 = glv_split(fields.canon(ORDER, k1))  # G half-scalars
    b1, t1, b2, t2 = glv_split(fields.canon(ORDER, k2))  # Q half-scalars
    # Digit streams stacked on a leading term axis: (33, 4) + batch.
    digits = jnp.stack(
        [
            jnp.broadcast_to(_glv_nibbles_msb(a), (_GLV_NWIN,) + batch)
            for a in (a1, a2, b1, b2)
        ],
        axis=1,
    )

    # Stacked per-term Jacobian tables, (16, 4) + batch + (L,).  G/phi(G)
    # entries are compile-time constants with z = 1 (z = 0 at digit 0);
    # Q/phi(Q) come from the per-batch window table.
    ones = jnp.broadcast_to(jnp.asarray(FIELD.const(1)), batch + (_L,))

    def bc(tab):  # (16, L) constant -> (16,) + batch + (L,)
        return jnp.broadcast_to(
            jnp.asarray(tab)[(slice(None),) + (None,) * len(batch)],
            (16,) + batch + (_L,),
        )

    g_z = jnp.concatenate(
        [jnp.zeros_like(ones)[None], jnp.broadcast_to(ones, (15,) + batch + (_L,))]
    )
    tx = jnp.stack([bc(_G_TAB_X), bc(_GP_TAB_X), qtx, qptx], axis=1)
    ty = jnp.stack([bc(_G_TAB_Y), bc(_G_TAB_Y), qty, qty], axis=1)
    tz = jnp.stack([g_z, g_z, qtz, qtz], axis=1)
    # Per-term negation flags, (4,) + batch: negate y at gather time.
    neg = jnp.stack([s1, s2, t1, t2], axis=0)

    def body(acc, d):
        # 4 doublings of all four accumulator lanes (infinity-safe)
        acc = _double4(acc)
        y = _one_hot_select(d, ty)
        y = fields.select(neg, fields.sub(FIELD, jnp.zeros_like(y), y), y)
        addend = JacobianPoint(_one_hot_select(d, tx), y, _one_hot_select(d, tz))
        return point_add(acc, addend), None

    acc, _ = jax.lax.scan(body, point_infinity((4,) + batch), digits)
    # Combine the four lanes with two MORE calls to the SAME ``(4,) + batch``
    # complete add the ladder body uses, padding spent lanes with infinity.
    # Nested-jit point ops dedup per input shape: a (2,)+batch pair-add plus
    # a batch-shaped final add each instantiate their own point_add AND
    # embedded point_double functions (~13k stablehlo lines — a third of the
    # fused 8-lane certify program), while two wasted infinity lanes cost a
    # few VPU ops.  Trace size is compile time on XLA:CPU.
    def _pad4(pt: JacobianPoint, lanes: slice) -> JacobianPoint:
        x = pt.x[lanes]
        pinf = point_infinity((4 - x.shape[0],) + batch)
        return JacobianPoint(
            jnp.concatenate([x, pinf.x]),
            jnp.concatenate([pt.y[lanes], pinf.y]),
            jnp.concatenate([pt.z[lanes], pinf.z]),
        )

    half = point_add(_pad4(acc, slice(0, None, 2)), _pad4(acc, slice(1, None, 2)))
    out = point_add(_pad4(half, slice(0, 1)), _pad4(half, slice(1, 2)))
    return JacobianPoint(out.x[0], out.y[0], out.z[0])


def _in_scalar_range(v: jnp.ndarray) -> jnp.ndarray:
    """``0 < v < N`` for a raw (possibly unreduced 256-bit) limb vector."""
    c = fields.exact_carry(v)
    nonzero = jnp.any(c != 0, axis=-1)
    below = ~fields.ge_const(c, ORDER.limbs)
    return nonzero & below


# N mod P as a field constant, and the canonical limbs of P - N, for the
# "second solution" branch of the x == r (mod N) check in verify.
_N_AS_FIELD = FIELD.const(N)
_P_MINUS_N = fields.to_limbs([P - N], _L)[0]


@jax.jit
def ecdsa_verify(
    qx: jnp.ndarray,
    qy: jnp.ndarray,
    z: jnp.ndarray,
    r: jnp.ndarray,
    s: jnp.ndarray,
) -> jnp.ndarray:
    """Batched ECDSA verification; returns a boolean mask.

    Inputs are limb vectors broadcast over leading batch axes: affine public
    key ``(qx, qy)``, digest-as-scalar ``z`` (already reduced mod N by the
    packing layer), and signature ``(r, s)`` as raw 256-bit values (range
    checks happen here, on device).
    """
    ok_range = _in_scalar_range(r) & _in_scalar_range(s)
    # raw 256-bit s is semi-reduced for ORDER (s < 2**256 < 2N), so the
    # tree/Fermat inverse applies directly.
    w = _inv_lanes(ORDER, s)
    u1 = fields.mul(ORDER, z, w)
    u2 = fields.mul(ORDER, r, w)
    pt = ecmul2_base(u1, u2, qx, qy)
    not_inf = ~is_infinity(pt)
    # x-coordinate equality mod N: affine x < P, r < N, and P < 2N, so the
    # only candidates are x == r and (when r + N < P) x == r + N.
    zinv = _inv_lanes(FIELD, pt.z)
    x_aff = fields.mul(FIELD, pt.x, fields.sqr(FIELD, zinv))
    r_canon = fields.canon(ORDER, r)
    eq1 = fields.eq_mod(FIELD, x_aff, r_canon)
    r_small = ~fields.ge_const(r_canon, _P_MINUS_N)
    eq2 = fields.eq_mod(
        FIELD, x_aff, fields.add(FIELD, r_canon, jnp.asarray(_N_AS_FIELD))
    )
    return ok_range & not_inf & (eq1 | (r_small & eq2))


# (P + 1) // 4: square-root exponent for P === 3 (mod 4).
_SQRT_EXP = (P + 1) // 4


@jax.jit
def ecdsa_recover(
    z: jnp.ndarray,
    r: jnp.ndarray,
    s: jnp.ndarray,
    v: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Batched public-key recovery (Ethereum-style ecrecover).

    ``v`` is the recovery id (0 or 1 — the y-parity bit; ids 2/3, i.e.
    r >= P - N overflow, are rejected as Ethereum consensus does in
    practice).  Returns ``(x, y, ok)`` with canonical affine coordinates;
    lanes with ``ok == False`` have unspecified coordinates.

    This is the engine's ``IsValidValidator`` hot path: sender identity is
    *recovered* from the signature and compared against the claimed address,
    exactly one ladder per message.
    """
    ok = _in_scalar_range(r) & _in_scalar_range(s)
    ok = ok & ((v == 0) | (v == 1))

    f = FIELD
    x = fields.canon(ORDER, r)  # r < N < P: also a canonical field element
    # y = sqrt(x^3 + 7); P === 3 (mod 4) so sqrt = pow((P+1)/4).  The
    # square root (mod P) and r^-1 (mod N) are data-independent, so they
    # ride ONE merged scan — two sequential ~64-window chains would double
    # the pre-ladder latency (fields.pow_fixed2).
    y2 = fields.add(f, fields.mul(f, fields.sqr(f, x), x), jnp.asarray(f.const(7)))
    y, rinv = fields.pow_fixed2(f, y2, _SQRT_EXP, ORDER, x, N - 2)
    ok = ok & fields.eq_mod(f, fields.sqr(f, y), y2)  # r was a valid x-coord
    y_canon = fields.canon(f, y)
    parity = (y_canon[..., 0] & 1).astype(jnp.int32)
    y_neg = fields.canon(f, fields.sub(f, jnp.zeros_like(y_canon), y_canon))
    y_sel = fields.select(parity == v.astype(jnp.int32), y_canon, y_neg)

    # Q = r^-1 * (s*R - z*G)  ==  (-z * r^-1)*G + (s * r^-1)*R
    u1 = fields.mul(
        ORDER, fields.sub(ORDER, jnp.zeros_like(z), z), rinv
    )
    u2 = fields.mul(ORDER, s, rinv)
    q = ecmul2_base(u1, u2, x, y_sel)
    ok = ok & ~is_infinity(q)
    qx, qy = to_affine(q)
    return qx, qy, ok
