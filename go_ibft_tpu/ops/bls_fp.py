"""Montgomery Fp/Fp2 arithmetic for BLS12-381 on TPU (JAX/XLA).

The 381-bit BLS12-381 prime sits far below the next radix-2**13 limb
boundary (2**390), so the pseudo-Mersenne folding of :mod:`.fields` cannot
apply (``fields.py`` raises at Modulus construction).  This module is the
promised Montgomery path: elements live in the Montgomery domain
``aR mod p`` with ``R = 2**390``, multiplication is a fully *parallel*
REDC — three schoolbook convolutions and log-depth carries, no sequential
limb recurrence — and additions/subtractions are lazy.

Correctness armor: every value is wrapped in :class:`FV`, which carries an
EXACT Python-int upper bound on the represented value.  All ops assert
their overflow preconditions against these bounds **at trace time** — a
formula that could overflow int32 lanes or exceed the REDC input range
fails loudly during ``jit`` tracing instead of silently corrupting field
math (the round-1 lesson: quiet big-int bugs cost an entire round).

Subtraction uses *bound-shaped* fat offsets: ``a - b`` becomes
``a + F - b`` where ``F`` is the smallest multiple of p whose limbs
dominate ``b``'s limb bounds.  Because a carried value's top limbs are
bounded by the value itself, F costs only ~3x the subtrahend's bound —
not the ~R/p blowup a uniform fat representation would need.

Reference context: go-ibft injects all crypto via Backend
(core/backend.go:37-56); BASELINE.md config #4 sets the BLS target.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .fields import LIMB_BITS, LIMB_MASK, _carry, _conv, _ks_carry, to_limbs

__all__ = [
    "P",
    "L",
    "R_MONT",
    "FV",
    "const",
    "to_mont",
    "from_mont_limbs",
    "pack_mont",
    "add",
    "sub",
    "neg",
    "mul",
    "sqr",
    "muli",
    "renorm",
    "inv",
    "pow_p_fixed",
    "select",
    "is_zero",
    "eq",
    "canon_mod_p",
    "f2_add",
    "f2_sub",
    "f2_neg",
    "f2_mul",
    "f2_sqr",
    "f2_muli",
    "f2_conj",
    "f2_mul_xi",
    "f2_inv",
    "f2_select",
    "f2_is_zero",
    "f2_renorm",
    "F2",
]

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
L = 30  # ceil(381 / 13)
R_MONT = 1 << (LIMB_BITS * L)  # 2**390
_PPRIME = (-pow(P, -1, R_MONT)) % R_MONT  # -p^-1 mod R
_P_LIMBS = to_limbs([P], L)[0]
_PPRIME_LIMBS = to_limbs([_PPRIME], L)[0]

ONE_M_INT = R_MONT % P  # to_mont(1)

# REDC output bound: t = (z + m*p)/R with m < R*(1 + 2**-12) =>
# t < z/R + p*(1 + 2**-12).  Inputs must satisfy z < _REDC_MAX_Z so t < 2p.
_REDC_MAX_Z = (P - (P >> 10)) * R_MONT

# Canonical "renormed" bound: every renorm_to output carries exactly this
# bound, so lax.scan state (whose pytree must be invariant) can hold FVs.
RN_BOUND = P + (P >> 3)

# Largest value a 30-limb carried vector can represent (limbs <= 2**13).
_NARROW_CAP = sum((LIMB_MASK + 1) << (LIMB_BITS * i) for i in range(L))


class FV:
    """A limb array + an exact host-side value bound.

    Registered as a pytree with ``bound`` as STATIC aux data: the bound is
    a (potentially 700-bit) Python int that exists only at trace time —
    ``jit``/``scan`` never see it as a traced value.
    """

    __slots__ = ("arr", "bound")

    def __init__(self, arr, bound: int):
        self.arr = arr
        self.bound = bound  # exclusive upper bound on the represented value

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"FV(shape={getattr(self.arr, 'shape', None)}, bound~2^{self.bound.bit_length()})"


jax.tree_util.register_pytree_node(
    FV,
    lambda v: ((v.arr,), v.bound),
    lambda bound, children: FV(children[0], bound),
)


def const(value_mod_p: int, nlimbs: int = L) -> FV:
    """Plain (non-Montgomery) constant as an FV.

    Kept as NUMPY so importing this module never initializes a JAX
    backend; jit tracing converts the arrays to device constants."""
    v = value_mod_p % P
    return FV(to_limbs([v], nlimbs)[0], v + 1)


def to_mont(value: int) -> FV:
    """Host int -> Montgomery-domain FV constant."""
    return const(value * R_MONT % P)


def pack_mont(values, batch_shape=None) -> np.ndarray:
    """Host packing: python ints -> Montgomery limb rows ``(N, L)``."""
    return to_limbs([v * R_MONT % P for v in values], L)


def from_mont_limbs(arr) -> list:
    """Host unpacking: Montgomery limb rows -> python ints (exact)."""
    from .fields import from_limbs

    rinv = pow(R_MONT, -1, P)
    return [v * rinv % P for v in from_limbs(arr)]


ONE = to_mont(1)
ZERO = FV(np.zeros((L,), np.int32), 1)


# -- fat offsets for borrow-free subtraction --------------------------------

_fat_cache: dict = {}


def _fat_for(sub_bound: int, nlimbs: int) -> Tuple[np.ndarray, int]:
    """Smallest-ish multiple of p whose limbs dominate any carried value
    < ``sub_bound``; returns (limbs, value).  Limb caps stay < 3*2**13 so
    ``a + F - b`` columns fit int32 with room to spare."""
    key = (sub_bound, nlimbs)
    hit = _fat_cache.get(key)
    if hit is not None:
        return hit
    floors = [
        min(LIMB_MASK + 1, sub_bound >> (LIMB_BITS * i)) for i in range(nlimbs)
    ]
    base = sum(f << (LIMB_BITS * i) for i, f in enumerate(floors))
    cap = 3 * (LIMB_MASK + 1) - 1
    m = -(-base // P)
    while True:
        value = m * P
        rem = value - base
        limbs = np.zeros(nlimbs, dtype=np.int32)
        for i in range(nlimbs - 1, -1, -1):
            unit = 1 << (LIMB_BITS * i)
            extra = min(rem // unit, cap - floors[i])
            limbs[i] = floors[i] + extra
            rem -= extra * unit
        if rem == 0:
            _fat_cache[key] = (limbs, value)
            return limbs, value
        m += 1  # pragma: no cover - greedy nearly always fits on first try


# -- narrow (30-limb) ops ---------------------------------------------------


def add(a: FV, b: FV) -> FV:
    out = _carry(a.arr + b.arr, 2)
    bound = a.bound + b.bound
    assert bound <= _NARROW_CAP, "narrow add overflow - renorm an operand"
    return FV(out, bound)


def sub(a: FV, b: FV) -> FV:
    fat_limbs, fat_value = _fat_for(b.bound, L)
    out = _carry(a.arr + jnp.asarray(fat_limbs) - b.arr, 3)
    bound = a.bound + fat_value
    assert bound <= _NARROW_CAP, "narrow sub overflow - renorm an operand"
    return FV(out, bound)


def neg(a: FV) -> FV:
    return sub(ZERO, a)


def muli(a: FV, k: int) -> FV:
    assert 1 <= k <= 8
    out = _carry(a.arr * k, 3)
    bound = a.bound * k
    assert bound <= _NARROW_CAP
    return FV(out, bound)


def _mul_wide(a: FV, b: FV) -> FV:
    """Full product as a 61-limb lazy vector (no reduction)."""
    z = _carry(_conv(a.arr, b.arr, 2 * L + 1), 4)
    return FV(z, a.bound * b.bound)


def _redc(z: FV) -> FV:
    """Parallel Montgomery reduction: 61-limb product -> <2p, exact limbs."""
    assert z.bound < _REDC_MAX_Z, "REDC input out of range - renorm operands"
    arr = z.arr
    if arr.shape[-1] < 2 * L + 2:
        pad = [(0, 0)] * (arr.ndim - 1) + [(0, 2 * L + 2 - arr.shape[-1])]
        arr = jnp.pad(arr, pad)
    # m = (z mod R) * p' mod R: take the low L columns of the uncarried
    # convolution (higher columns are multiples of R), then carry — the
    # carry out of the top limb is dropped by _carry, which is again mod R.
    m = _carry(
        _conv(arr[..., :L], jnp.asarray(_PPRIME_LIMBS), 2 * L - 1)[..., :L], 4
    )
    u = arr + _conv(m, jnp.asarray(_P_LIMBS), 2 * L + 2)
    u = _ks_carry(_carry(u, 4))
    # u == z + m*p is divisible by R; exact carries make the low limbs
    # literally zero, so the division is a slice.
    t = u[..., L : 2 * L]
    return FV(t, z.bound // R_MONT + P + (P >> 10))


def mul(a: FV, b: FV) -> FV:
    return _redc(_mul_wide(a, b))


def sqr(a: FV) -> FV:
    return mul(a, a)


def renorm(a: FV) -> FV:
    """Re-reduce a lazy accumulation to < 2p (one REDC against R mod p)."""
    return mul(a, ONE)


def renorm_to(a: FV) -> FV:
    """Renorm with the FIXED bound :data:`RN_BOUND` — scan-state safe."""
    out = mul(a, ONE)
    assert out.bound <= RN_BOUND, out.bound
    return FV(out.arr, RN_BOUND)


def select(cond: jnp.ndarray, a: FV, b: FV) -> FV:
    return FV(
        jnp.where(cond[..., None], a.arr, b.arr), max(a.bound, b.bound)
    )


def _canon_exact(a: FV) -> jnp.ndarray:
    """Exact canonical limbs in [0, p); input bound must be < 4p.

    Sequential scans inside — edges only (final equality checks)."""
    from .fields import _ge_const, _sub_exact, _exact_carry

    assert a.bound <= 4 * P
    z = _exact_carry(a.arr)
    for _ in range(3):  # peel up to 3 multiples of p
        ge = _ge_const(z, _P_LIMBS)
        z = jnp.where(ge[..., None], _sub_exact(z, _P_LIMBS), z)
    return z


def canon_mod_p(a: FV) -> jnp.ndarray:
    return _canon_exact(a)


def is_zero(a: FV) -> jnp.ndarray:
    """a === 0 (mod p), branch-free, for bounds up to 8p: the KS-canonical
    value must equal one of the k multiples of p below the bound."""
    assert a.bound <= 8 * P, "is_zero bound too large - renorm first"
    c = _ks_carry(a.arr)
    k = -(-a.bound // P)
    hit = jnp.zeros(c.shape[:-1], dtype=bool)
    for j in range(k + 1):
        ref = jnp.asarray(to_limbs([j * P], L)[0])
        hit = hit | jnp.all(c == ref, axis=-1)
    return hit


def eq(a: FV, b: FV) -> jnp.ndarray:
    return is_zero(sub(renorm(a) if a.bound > 4 * P else a,
                       renorm(b) if b.bound > 4 * P else b))


def pow_p_fixed(a: FV, exponent: int) -> FV:
    """Montgomery-domain fixed-exponent power via an MSB-first scan.

    ``mont_pow(aR, e) == (a^e)R`` — the domain survives the ladder."""
    assert exponent > 0
    nbits = exponent.bit_length()
    bits = jnp.asarray(
        [(exponent >> i) & 1 for i in range(nbits - 2, -1, -1)], dtype=bool
    )
    a2 = renorm(a) if a.bound > 2 * P + (P >> 9) else a
    bnd = a2.bound

    def body(acc_arr, bit):
        acc = FV(acc_arr, bnd)
        acc = sqr(acc)
        acc = select(jnp.broadcast_to(bit, acc.arr.shape[:-1]), mul(acc, a2), acc)
        return acc.arr, None

    out, _ = jax.lax.scan(body, a2.arr, bits)
    return FV(out, bnd)


def inv(a: FV) -> FV:
    """Fermat inverse (montgomery domain in, montgomery domain out);
    inv(0) == 0."""
    return pow_p_fixed(a, P - 2)


# -- Fp2 = Fp[u]/(u^2 + 1) --------------------------------------------------


class F2(NamedTuple):
    c0: FV
    c1: FV


F2_ZERO = F2(ZERO, ZERO)
F2_ONE = F2(ONE, ZERO)


def f2_add(a: F2, b: F2) -> F2:
    return F2(add(a.c0, b.c0), add(a.c1, b.c1))


def f2_sub(a: F2, b: F2) -> F2:
    return F2(sub(a.c0, b.c0), sub(a.c1, b.c1))


def f2_neg(a: F2) -> F2:
    return F2(neg(a.c0), neg(a.c1))


def f2_conj(a: F2) -> F2:
    return F2(a.c0, neg(a.c1))


def f2_muli(a: F2, k: int) -> F2:
    return F2(muli(a.c0, k), muli(a.c1, k))


def f2_mul(a: F2, b: F2) -> F2:
    """Karatsuba with LAZY combination: the three products stay wide and the
    (t0 - t1) / cross-term subtractions happen on 61-limb vectors, costing
    one REDC per output component."""
    t0 = _mul_wide(a.c0, b.c0)
    t1 = _mul_wide(a.c1, b.c1)
    t2 = _mul_wide(add(a.c0, a.c1), add(b.c0, b.c1))
    fat_limbs, fat_value = _fat_for(t1.bound, 2 * L + 1)
    c0 = _redc(
        FV(_carry(t0.arr + jnp.asarray(fat_limbs) - t1.arr, 3), t0.bound + fat_value)
    )
    cross_sub = FV(
        _carry(t0.arr + t1.arr, 2), t0.bound + t1.bound
    )
    fat2_limbs, fat2_value = _fat_for(cross_sub.bound, 2 * L + 1)
    c1 = _redc(
        FV(
            _carry(t2.arr + jnp.asarray(fat2_limbs) - cross_sub.arr, 3),
            t2.bound + fat2_value,
        )
    )
    return F2(c0, c1)


def f2_sqr(a: F2) -> F2:
    """(c0+c1 u)^2 = (c0+c1)(c0-c1) + 2 c0 c1 u."""
    s = add(a.c0, a.c1)
    d = sub(a.c0, a.c1)
    t = _mul_wide(a.c0, a.c1)
    c1 = _redc(FV(_carry(t.arr * 2, 2), 2 * t.bound))
    return F2(mul(s, d), c1)


def f2_mul_xi(a: F2) -> F2:
    """Multiply by xi = 1 + u: (c0 - c1) + (c0 + c1) u."""
    return F2(sub(a.c0, a.c1), add(a.c0, a.c1))


def f2_inv(a: F2) -> F2:
    n = add(mul(a.c0, a.c0), mul(a.c1, a.c1))
    ninv = inv(n)
    return F2(mul(a.c0, ninv), neg(mul(a.c1, ninv)))


def f2_select(cond: jnp.ndarray, a: F2, b: F2) -> F2:
    return F2(select(cond, a.c0, b.c0), select(cond, a.c1, b.c1))


def f2_is_zero(a: F2) -> jnp.ndarray:
    a0 = renorm(a.c0) if a.c0.bound > 8 * P else a.c0
    a1 = renorm(a.c1) if a.c1.bound > 8 * P else a.c1
    return is_zero(a0) & is_zero(a1)


def f2_renorm(a: F2) -> F2:
    return F2(renorm(a.c0), renorm(a.c1))


def f2_const(c0: int, c1: int) -> F2:
    """Host ints -> Montgomery-domain Fp2 constant."""
    return F2(to_mont(c0), to_mont(c1))
