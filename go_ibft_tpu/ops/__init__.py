"""TPU-native batched math ops: limbed big-integer fields, elliptic curves,
Keccak hashing.

This package is the data plane of the framework — the reference's expensive
per-message ``Verifier`` predicates (go-ibft core/backend.go:37-56, driven
one message at a time under the store lock in messages/messages.go:183-198)
become fixed-shape, ``jit``/``vmap``-compiled batch kernels here
(SURVEY.md §7 stage 4).
"""
