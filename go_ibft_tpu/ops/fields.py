"""Limbed modular big-integer arithmetic on TPU (JAX/XLA).

TPUs have no native wide-integer types, so 256-bit field elements are
represented as vectors of radix-2**13 limbs held in ``int32`` lanes
(SURVEY.md §7 hard part (a)).  The radix is chosen so that schoolbook
multiplication never overflows int32:

* a limb product is < 2**26,
* a convolution column sums at most ``nlimbs`` (= 20 for 256-bit fields)
  such products, staying < 20 * 2**26 < 2**31.

All values are kept **unsigned and "semi-reduced"**: limbs lie in
``[0, 2**13]`` (the upper bound is *inclusive* — lazy carries may leave a
limb at exactly 2**13, which the overflow analysis above still admits) and
the represented value lies in ``[0, 2*p)``.  Subtraction never produces
negative limbs: ``a - b`` is computed as ``a + F - b`` where ``F`` is a
precomputed *fat* representation of ``K*p`` whose every limb is >= 2**13.
Exact canonicalization to ``[0, p)`` (sequential carry scans) happens only
at the edges — final comparisons and host I/O — never inside hot loops.

Reduction uses generalized pseudo-Mersenne folding: ``2**(13*L) === c_fold``
and ``2**bits === c_fb (mod p)``, with the fold schedule derived statically
from value bounds at :class:`Modulus` construction time.  This requires the
modulus to sit close under a power of two (``128 * c_fb < p``) — true for
both secp256k1 moduli; BLS12-381 uses the Montgomery path instead (see
``bls12_381.py``).

Everything here is shape-static, branch-free, and batched by broadcasting
over leading axes, so a whole round's worth of signatures reduces in one
``jit`` — replacing the reference's per-message sequential verifies
(go-ibft messages/messages.go:183-198, core/backend.go:37-56).
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

LIMB_BITS = 13
LIMB_MASK = (1 << LIMB_BITS) - 1

__all__ = [
    "LIMB_BITS",
    "LIMB_MASK",
    "Modulus",
    "to_limbs",
    "from_limbs",
    "add",
    "sub",
    "mul",
    "sqr",
    "muli",
    "pow_fixed",
    "pow_fixed2",
    "select16",
    "inv",
    "batch_inv",
    "canon",
    "is_zero",
    "eq_mod",
    "select",
    "exact_carry",
    "ge_const",
]


def to_limbs(values: Sequence[int], nlimbs: int) -> np.ndarray:
    """Host-side: pack python ints into an ``(N, nlimbs)`` int32 limb array."""
    out = np.zeros((len(values), nlimbs), dtype=np.int32)
    for row, v in enumerate(values):
        if v < 0:
            raise ValueError("limb representation is unsigned")
        for i in range(nlimbs):
            out[row, i] = v & LIMB_MASK
            v >>= LIMB_BITS
        if v:
            raise ValueError(f"value does not fit in {nlimbs} limbs")
    return out


def from_limbs(arr) -> List[int]:
    """Host-side: unpack an ``(..., nlimbs)`` limb array into python ints."""
    a = np.asarray(arr, dtype=np.int64)
    flat = a.reshape(-1, a.shape[-1])
    out = []
    for row in flat:
        v = 0
        for i in range(a.shape[-1] - 1, -1, -1):
            v = (v << LIMB_BITS) + int(row[i])
        out.append(v)
    return out


def _single_limbs(value: int, nlimbs: int) -> np.ndarray:
    return to_limbs([value], nlimbs)[0]


def _fat_rep(value: int, nlimbs: int) -> np.ndarray:
    """Limb rep of ``value`` with every limb in [2**13, 3 * 2**13).

    Adding it makes limb-wise subtraction borrow-free.  Exists whenever
    ``value`` >= sum(2**13 * 2**(13 i)) and fits the per-limb cap.
    """
    lo_floor = LIMB_MASK + 1
    cap = 3 * lo_floor - 1
    rem = value - sum(lo_floor << (LIMB_BITS * i) for i in range(nlimbs))
    if rem < 0:
        raise ValueError("value too small for fat representation")
    out = np.zeros(nlimbs, dtype=np.int32)
    for i in range(nlimbs - 1, -1, -1):
        unit = 1 << (LIMB_BITS * i)
        extra = min(rem // unit, cap - lo_floor)
        out[i] = lo_floor + extra
        rem -= extra * unit
    if rem:
        raise ValueError("fat representation infeasible")
    return out


class Modulus:
    """Static per-modulus data: limbs, fold constants, fat K*p rep.

    Construction precomputes everything the traced ops need as numpy
    constants, so a ``Modulus`` instance can be closed over inside ``jit``.
    """

    def __init__(self, p: int):
        if p <= 0:
            raise ValueError("modulus must be positive")
        self.p = p
        self.bits = p.bit_length()
        self.nlimbs = -(-self.bits // LIMB_BITS)
        L = self.nlimbs
        self.limbs = _single_limbs(p, L)
        # Fold constants: 2**(13 L) and 2**bits mod p.
        self.c_fold = (1 << (LIMB_BITS * L)) % p
        self.c_fb = (1 << self.bits) % p  # == 2**bits - p
        if self.c_fb == 0 or 128 * self.c_fb >= p:
            raise ValueError(
                "modulus too far below a power of two for folding; "
                "use the Montgomery path"
            )
        self.c_fold_limbs = _single_limbs(self.c_fold, -(-self.c_fold.bit_length() // LIMB_BITS))
        self.c_fb_limbs = _single_limbs(self.c_fb, -(-max(self.c_fb.bit_length(), 1) // LIMB_BITS))
        self.fb_limb, self.fb_shift = divmod(self.bits, LIMB_BITS)
        # Fat K*p for borrow-free subtraction of any semi-reduced (< 2p) value.
        k = 3
        while k * p < sum((LIMB_MASK + 1) << (LIMB_BITS * i) for i in range(L)):
            k += 1
        self.fat_kp = _fat_rep(k * p, L)
        self.fat_k = k
        self.sub_bound = 2 * p + k * p  # value bound of a + K p - b

    def const(self, value: int) -> np.ndarray:
        """Limbs of ``value mod p`` as a broadcastable ``(nlimbs,)`` array."""
        return _single_limbs(value % self.p, self.nlimbs)


def _carry(z: jnp.ndarray, passes: int) -> jnp.ndarray:
    """Lazy parallel carry: each pass moves carries one limb up.

    With unsigned inputs bounded < 2**31 the limb values converge to
    ``[0, 2**13]`` in <= 4 passes (see module docstring).  The caller must
    size ``z`` so the top limb never produces a carry.
    """
    zero = jnp.zeros(z.shape[:-1] + (1,), dtype=z.dtype)
    for _ in range(passes):
        c = z >> LIMB_BITS
        z = (z & LIMB_MASK) + jnp.concatenate([zero, c[..., :-1]], axis=-1)
    return z


def _conv(a: jnp.ndarray, b: jnp.ndarray, out_len: int) -> jnp.ndarray:
    """Schoolbook product columns (no carries): ``out[k] = sum_i a_i*b_(k-i)``.

    Implemented as ONE outer product + a shear-by-reshape + a row
    reduction (~7 HLO ops), not ``la`` shifted pad-adds (~7*la ops): a
    field `mul` built from the unrolled form lowered to ~800 stablehlo
    lines, and with ~50 muls inside every ladder-scan body, trace size
    WAS the XLA:CPU compile time (265 s for the smallest certify program,
    VERDICT r04 weak #3).  The shear: row ``i`` of the padded outer
    product holds ``a_i * b`` at columns 0..lb-1 of width ``W``;
    re-viewing the flat buffer with rows one element NARROWER shifts row
    ``i`` right by ``i``, so a plain column sum produces the convolution.
    The wrapped tail a narrower view reads from the previous row lands in
    that row's zero padding (W >= out_len + la guarantees it).  Also
    serves truncated products down to ``out_len >= lb - 1``: columns at
    or beyond ``out_len`` fall off the slice — exact int32 column sums
    either way (bounds unchanged: <= la * 2**26 < 2**31).  Truncating
    below ``lb - 1`` would let a narrower view's wrapped tail land inside
    retained columns (silently wrong sums), hence the assert.
    """
    la, lb = a.shape[-1], b.shape[-1]
    if out_len < lb - 1:
        raise ValueError(
            f"shear conv requires out_len >= lb - 1 ({out_len} < {lb - 1})"
        )
    w = out_len + la
    outer = a[..., :, None] * b[..., None, :]  # (..., la, lb)
    batch = outer.shape[:-2]
    x = jnp.pad(outer, [(0, 0)] * len(batch) + [(0, 0), (0, w - lb)])
    flat = x.reshape(batch + (la * w,))
    sheared = flat[..., : la * (w - 1)].reshape(batch + (la, w - 1))
    return jnp.sum(sheared, axis=-2)[..., :out_len]


def _pad_to(z: jnp.ndarray, n: int) -> jnp.ndarray:
    if z.shape[-1] >= n:
        return z
    pad = [(0, 0)] * (z.ndim - 1) + [(0, n - z.shape[-1])]
    return jnp.pad(z, pad)


def _fold_semi(m: Modulus, z: jnp.ndarray, bound: int) -> jnp.ndarray:
    """Reduce a carried, unsigned limb vector of known value ``bound`` to a
    semi-reduced (< 2p) ``nlimbs`` vector.  Fold schedule is static."""
    L = m.nlimbs
    lw = LIMB_BITS * L
    c_fold = jnp.asarray(m.c_fold_limbs)
    while bound >= (1 << (lw + 6)):
        lo, hi = z[..., :L], z[..., L:]
        hi_bound = (bound >> lw) + 1
        # Truncate provably-zero top limbs of hi (unsigned => value-bounded).
        hi_len = min(hi.shape[-1], -(-hi_bound.bit_length() // LIMB_BITS) + 1)
        hi = hi[..., :hi_len]
        prod_bound = hi_bound * m.c_fold
        out_len = max(L, hi_len + c_fold.shape[-1]) + 1
        prod = _carry(_conv(hi, c_fold, out_len), 4)
        z = _carry(_pad_to(lo, out_len) + prod, 2)
        bound = (1 << lw) + prod_bound
    # Final fold at bit position m.bits: v = lo + hi * 2**bits === lo + hi*c_fb.
    z = _pad_to(z, L + 2)[..., : L + 2]
    fbl, fbs = m.fb_limb, m.fb_shift
    hi = z[..., fbl] >> fbs
    for j in range(fbl + 1, z.shape[-1]):
        hi = hi + (z[..., j] << (LIMB_BITS * (j - fbl) - fbs))
    lo = z[..., :L]
    mask_col = jnp.asarray(
        [(1 << fbs) - 1 if i == fbl else LIMB_MASK + 1 for i in range(L)],
        dtype=jnp.int32,
    )
    # (the +1 sentinel leaves limbs below fbl untouched: x & (2**13) is wrong —
    #  so use a where instead of a mask for clarity)
    keep = jnp.asarray([i < fbl for i in range(L)])
    lo = jnp.where(keep, lo, lo & mask_col)
    cf = jnp.asarray(m.c_fb_limbs)
    prod = hi[..., None] * cf  # hi < 2**7, limb < 2**13 -> < 2**20, int32-safe
    return _carry(lo + _pad_to(prod, L), 3)


def add(m: Modulus, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a + b) mod-class, semi-reduced output."""
    z = _carry(_pad_to(a + b, m.nlimbs + 1), 2)
    return _fold_semi(m, z, 4 * m.p)


def sub(m: Modulus, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a - b) mod-class via the borrow-free fat K*p trick."""
    z = _carry(_pad_to(a + jnp.asarray(m.fat_kp) - b, m.nlimbs + 1), 3)
    return _fold_semi(m, z, m.sub_bound)


def mul(m: Modulus, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a * b) mod-class: schoolbook conv + static fold schedule."""
    bound = (2 * m.p) ** 2
    out_len = -(-bound.bit_length() // LIMB_BITS) + 1
    z = _carry(_conv(a, b, out_len), 4)
    return _fold_semi(m, z, bound)


def sqr(m: Modulus, a: jnp.ndarray) -> jnp.ndarray:
    return mul(m, a, a)


def muli(m: Modulus, a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant 1 <= k <= 16."""
    if not 1 <= k <= 16:
        raise ValueError("k out of range")
    z = _carry(_pad_to(a * k, m.nlimbs + 2), 3)
    return _fold_semi(m, z, 2 * m.p * k)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Branchless limb-vector select; ``cond`` broadcasts over the limb axis."""
    return jnp.where(cond[..., None], a, b)


def select16(sel: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
    """Branchless 16-way gather: ``table`` is ``(16, ..., L)`` (leading
    table axis), ``sel`` integer in [0, 16); returns ``(..., L)``.

    A 4-level tree of pure ``where`` ops (15 selects), NOT a one-hot
    ``einsum``: an int32 ``dot_general`` inside a scan body lowers poorly
    on TPU (no MXU int path — each becomes a serialized VPU contraction
    with layout shuffles; measured r03, scripts/ab_ladder_select.py)."""
    b0 = (sel & 1).astype(bool)[..., None]
    b1 = (sel & 2).astype(bool)[..., None]
    b2 = (sel & 4).astype(bool)[..., None]
    b3 = (sel & 8).astype(bool)[..., None]
    t = [jnp.where(b0, table[i + 1], table[i]) for i in range(0, 16, 2)]
    t = [jnp.where(b1, t[i + 1], t[i]) for i in range(0, 8, 2)]
    t = [jnp.where(b2, t[i + 1], t[i]) for i in range(0, 4, 2)]
    return jnp.where(b3, t[1], t[0])


def pow_fixed(m: Modulus, a: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """a**exponent with a fixed public exponent: 4-bit windowed scan.

    Left-to-right 2**4-ary exponentiation — per window 4 squarings + ONE
    table multiply (14 table-build muls up front), ~5.1 sequential muls
    per 4 bits vs the bit-serial square-and-multiply's 8 (that variant
    computes the conditional multiply unconditionally under a ``select``
    every step).  The scan body closes over the batch power table; window
    digits are static scan inputs, gathered via :func:`select16`.  This is
    the latency shape of the three per-recover Fermat scans the VERDICT r03
    flagged (s⁻¹, √, affine inverse).
    """
    if exponent < 0:
        raise ValueError("exponent must be non-negative")
    if exponent == 0:
        return jnp.broadcast_to(jnp.asarray(m.const(1)), a.shape)
    nwin = -(-exponent.bit_length() // 4)
    digits = _pow_digits(exponent, nwin)  # MSB-first
    table = _pow_table(m, a)  # (16, ..., L); scan-built (trace-compact)

    def body(acc, digit):
        for _ in range(4):
            acc = mul(m, acc, acc)
        acc = mul(m, acc, select16(digit, table))
        return acc, None

    acc, _ = jax.lax.scan(body, select16(jnp.asarray(digits[0]), table), jnp.asarray(digits[1:]))
    return acc


def inv(m: Modulus, a: jnp.ndarray) -> jnp.ndarray:
    """Modular inverse by Fermat (modulus must be prime); inv(0) == 0."""
    return pow_fixed(m, a, m.p - 2)


def _pow_digits(exponent: int, nwin: int) -> np.ndarray:
    return np.asarray(
        [(exponent >> (4 * j)) & 0xF for j in range(nwin - 1, -1, -1)],
        dtype=np.int32,
    )


def _pow_table(m: Modulus, a: jnp.ndarray) -> jnp.ndarray:
    """Window table a^0..a^15, built with a 14-step scan (trace-compact)."""
    one = jnp.broadcast_to(jnp.asarray(m.const(1)), a.shape)

    def tab_body(prev, _):
        nxt = mul(m, prev, a)
        return nxt, nxt

    _, tail = jax.lax.scan(tab_body, a, None, length=14)  # a^2 .. a^15
    return jnp.concatenate([one[None], a[None], tail])


def pow_fixed2(
    m1: Modulus,
    a1: jnp.ndarray,
    e1: int,
    m2: Modulus,
    a2: jnp.ndarray,
    e2: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """TWO independent fixed-exponent powers in ONE windowed scan.

    The recover hot path needs a square root mod P and an inverse mod N —
    two data-independent ~64-window chains.  Two separate ``lax.scan``s
    execute strictly one after the other (XLA runs While loops
    sequentially), doubling the latency; one scan whose body advances both
    chains lets the VPU interleave them, so the pair costs barely more
    wall-clock than one (VERDICT r04 ask #2: the per-recover scan stack is
    the measured floor after the ladder).  Exponents are padded to a
    common window count with leading zero digits (digit 0 multiplies by
    table[0] == 1 — a wasted-but-harmless mul keeps the body branch-free).
    """
    if e1 <= 0 or e2 <= 0:
        raise ValueError("pow_fixed2 requires positive exponents")
    nwin = max(-(-e1.bit_length() // 4), -(-e2.bit_length() // 4))
    d1 = _pow_digits(e1, nwin)
    d2 = _pow_digits(e2, nwin)
    t1 = _pow_table(m1, a1)
    t2 = _pow_table(m2, a2)

    def body(carry, digits):
        acc1, acc2 = carry
        g1, g2 = digits
        for _ in range(4):
            acc1 = mul(m1, acc1, acc1)
            acc2 = mul(m2, acc2, acc2)
        acc1 = mul(m1, acc1, select16(g1, t1))
        acc2 = mul(m2, acc2, select16(g2, t2))
        return (acc1, acc2), None

    init = (select16(jnp.asarray(d1[0]), t1), select16(jnp.asarray(d2[0]), t2))
    (acc1, acc2), _ = jax.lax.scan(
        body, init, (jnp.asarray(d1[1:]), jnp.asarray(d2[1:]))
    )
    return acc1, acc2


def batch_inv(m: Modulus, a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product-tree inverse over the LEADING axis.

    One Fermat scan total — on the 1-lane root product — plus one batched
    mul per tree level in each direction (~2*log2(B)), instead of B
    parallel 329-mul scans: the VERDICT r04 ask #2 amortization.  Lanes
    that are 0 (mod p) are masked to 1 through the tree and forced back to
    0 on output, preserving the ``inv(0) == 0`` contract (infinity maps to
    (0, 0) in ``to_affine``).  Inputs semi-reduced; outputs semi-reduced.

    Both tree sweeps run as ``lax.scan``s over fixed ``(B/2, L)``-padded
    levels (valid entries keep a prefix-contiguous layout; pad lanes hold
    exact ones, which multiply through harmlessly): unrolled, the 2*log2(B)
    shrinking-shape muls each inline ~270 stablehlo lines — 5.2k lines at
    the 128-lane bucket — and trace size is compile time on XLA:CPU.
    """
    n = a.shape[0]
    if n == 1:
        return inv(m, a)
    zero = is_zero_fast(m, a)
    base = select(zero, jnp.broadcast_to(jnp.asarray(m.const(1)), a.shape), a)
    if n & (n - 1):  # pad to a power of two with exact ones
        pad = (1 << n.bit_length()) - n
        ones = jnp.broadcast_to(
            jnp.asarray(m.const(1)), (pad,) + base.shape[1:]
        )
        base = jnp.concatenate([base, ones])
    np2 = base.shape[0]
    if np2 == 2:
        root_inv = pow_fixed(m, mul(m, base[0:1], base[1:2]), m.p - 2)
        sib = jnp.stack([base[1::2], base[0::2]], axis=1).reshape(base.shape)
        invs = mul(m, jnp.repeat(root_inv, 2, axis=0), sib)
        return select(zero, jnp.zeros_like(a), invs[: a.shape[0]])

    half = np2 // 2
    ones_h = jnp.broadcast_to(jnp.asarray(m.const(1)), (half,) + base.shape[1:])

    def up_body(state, _):
        nxt = mul(m, state[0::2], state[1::2])  # valid prefix halves
        nxt = jnp.concatenate([nxt, ones_h[: half - nxt.shape[0]]])
        return nxt, nxt

    lvl1 = mul(m, base[0::2], base[1::2])  # (half, L), fully valid
    _, ups = jax.lax.scan(up_body, lvl1, None, length=np2.bit_length() - 2)
    root = ups[-1][0:1]  # (1, L) product of every lane
    root_inv = pow_fixed(m, root, m.p - 2)  # the ONE Fermat scan

    def down_body(invs, lvl):
        expanded = jnp.repeat(invs, 2, axis=0)[:half]
        sib = jnp.stack([lvl[1::2], lvl[0::2]], axis=1).reshape(lvl.shape)
        return mul(m, expanded, sib), None

    # Walk the stored levels back down: ups[:-1] reversed, then lvl1.
    down_levels = jnp.concatenate([ups[:-1][::-1], lvl1[None]])
    invs0 = jnp.concatenate([root_inv, ones_h[: half - 1]])
    invs, _ = jax.lax.scan(down_body, invs0, down_levels)
    # Final level: the padded inputs themselves, at full width.
    expanded = jnp.repeat(invs, 2, axis=0)[:np2]
    sib = jnp.stack([base[1::2], base[0::2]], axis=1).reshape(base.shape)
    out = mul(m, expanded, sib)
    return select(zero, jnp.zeros_like(a), out[: a.shape[0]])


def _exact_carry(z: jnp.ndarray) -> jnp.ndarray:
    """Sequential exact carry propagation (lax.scan over the limb axis)."""

    def step(carry, x):
        t = x + carry
        return t >> LIMB_BITS, t & LIMB_MASK

    xs = jnp.moveaxis(z, -1, 0)
    _, ys = jax.lax.scan(step, jnp.zeros(z.shape[:-1], dtype=z.dtype), xs)
    return jnp.moveaxis(ys, 0, -1)


def _ge_const(a: jnp.ndarray, ref: np.ndarray) -> jnp.ndarray:
    """a >= ref, both canonical; unrolled lexicographic compare from the top."""
    res = jnp.zeros(a.shape[:-1], dtype=jnp.int32)
    for i in range(a.shape[-1] - 1, -1, -1):
        d = jnp.sign(a[..., i] - int(ref[i]))
        res = jnp.where(res != 0, res, d)
    return res >= 0


def canon(m: Modulus, a: jnp.ndarray) -> jnp.ndarray:
    """Exact canonical form in [0, p) with limbs < 2**13.

    Input must be semi-reduced (< 2p).  Only used at the edges (final
    equality checks, host I/O): it contains sequential limb scans, which
    would serialize the hot loop.
    """
    z = _exact_carry(a)
    ge = _ge_const(z, m.limbs)
    # _sub_exact is only meaningful where z >= p (no final borrow); the
    # other lanes keep z.
    return select(ge, _sub_exact(z, m.limbs), z)


def _sub_exact(a: jnp.ndarray, ref: np.ndarray) -> jnp.ndarray:
    """a - ref for canonical a >= ref: sequential borrow scan."""

    def step(borrow, x):
        t = x + borrow
        b = t >> LIMB_BITS
        return b, t - (b << LIMB_BITS)

    xs = jnp.moveaxis(a - jnp.asarray(ref, dtype=jnp.int32), -1, 0)
    _, ys = jax.lax.scan(step, jnp.zeros(a.shape[:-1], dtype=a.dtype), xs)
    return jnp.moveaxis(ys, 0, -1)


# Public aliases: raw (non-modular) exact carry + lexicographic compare, used
# by curve code for range checks like ``r < n`` on unreduced 256-bit inputs.
exact_carry = _exact_carry
ge_const = _ge_const


def _ks_carry(a: jnp.ndarray) -> jnp.ndarray:
    """Exact carry for limbs in ``[0, 2**13]`` via Kogge-Stone prefix OR.

    The lazy-carry passes leave limbs with at most a single overflow bit, so
    carry propagation is binary and resolves in log2(nlimbs) *vector* steps —
    unlike :func:`_exact_carry`'s sequential ``lax.scan``, this keeps the
    256-step EC ladder free of inner serial chains (the single biggest
    runtime cost of the complete-addition exception tests).
    """
    g0 = a >> LIMB_BITS  # generate in {0, 1}
    base = a & LIMB_MASK
    zero = jnp.zeros(a.shape[:-1] + (1,), dtype=a.dtype)
    s = base + jnp.concatenate([zero, g0[..., :-1]], axis=-1)  # in [0, 2**13]
    gen = s >> LIMB_BITS
    prop = (s == LIMB_MASK).astype(a.dtype)
    # inclusive prefix: carry_out[i] = gen[i] | (prop[i] & carry_out[i-1])
    nl = a.shape[-1]
    pad_axes = [(0, 0)] * (a.ndim - 1)
    d = 1
    while d < nl:
        gen = gen | (prop & jnp.pad(gen[..., :-d], pad_axes + [(d, 0)]))
        prop = prop & jnp.pad(prop[..., :-d], pad_axes + [(d, 0)])
        d *= 2
    carry_in = jnp.concatenate([zero, gen[..., :-1]], axis=-1)
    return (s + carry_in) & LIMB_MASK


def canon_value(m: Modulus, a: jnp.ndarray) -> jnp.ndarray:
    """Unique canonical limbs of the *value* of a semi-reduced input.

    Input limbs must lie in ``[0, 2**13]`` (true for every op output here);
    the value stays in ``[0, 2p)`` — NOT reduced mod p.  Branch-free,
    scan-free (see :func:`_ks_carry`)."""
    return _ks_carry(a)


def is_zero_fast(m: Modulus, a: jnp.ndarray) -> jnp.ndarray:
    """``a === 0 (mod p)`` for semi-reduced ``a`` (< 2p): value 0 or p.

    Scan-free: canonical limbs are unique, so two vector compares decide."""
    c = _ks_carry(a)
    p_limbs = jnp.asarray(m.limbs)
    return jnp.all(c == 0, axis=-1) | jnp.all(c == p_limbs, axis=-1)


def is_zero(m: Modulus, a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canon(m, a) == 0, axis=-1)


def eq_mod(m: Modulus, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero(m, sub(m, a, b))
