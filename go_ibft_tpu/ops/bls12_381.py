"""BLS12-381 aggregate verification on TPU (JAX/XLA).

BASELINE.md config #4: one pairing equation certifies a whole quorum of
COMMIT seals — ``e(G1, sum(sig_i)) == e(sum(pk_i), H2(m))`` — replacing the
reference's per-message committed-seal loop (go-ibft core/ibft.go:931-944
driving Backend.IsValidCommittedSeal once per seal) with two aggregations,
two Miller loops and ONE final exponentiation on device.

Structure (everything over :mod:`.bls_fp`'s Montgomery Fp/Fp2):

* towers Fp6 = Fp2[v]/(v^3 - xi), Fp12 = Fp6[w]/(w^2 - v), xi = 1 + u —
  identical to the host oracle (:mod:`go_ibft_tpu.crypto.bls`), whose
  exact-int arithmetic is the differential reference for every stage;
* G1 (Fp) and G2 (Fp2) Jacobian point ops with branchless complete
  addition (selects, never Python control flow), used by log-depth masked
  TREE aggregation over the validator axis — the aggregation is the only
  O(V) work, the pairing cost is independent of validator count;
* the ate Miller loop over the 63 bits of |x| as one ``lax.scan`` (line
  add-steps are computed branchlessly and selected in — 6 of 63 bits are
  set, trading ~2x runtime for one compiled body);
* final exponentiation via the easy part + the 2020/875 hard-part chain
  ``(x-1)^2 (x+p)(x^2+p^2-1) + 3`` (identity verified against python ints
  at import), cyclotomic inverses as conjugation, Frobenius via
  precomputed ``xi^(k(p^n-1)/6)`` constants;
* the verification equation is checked as
  ``final_exp(m1 * m2^-1) == 1`` — one final exp for both pairings; line
  scalings by Fp2 subfield factors cancel under the final exponentiation,
  which is why device Miller values are only comparable to the host after
  it (tests compare ``final_exp3(device) == host_pairing**3``).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto import bls as host
from ..obs import ledger as cost_ledger
from . import bls_fp as fp
from .bls_fp import F2, FV, RN_BOUND, P

__all__ = [
    "G2Jac",
    "g1_aggregate",
    "g2_aggregate",
    "g1_merge_tree",
    "g2_merge_tree",
    "miller_loop",
    "final_exp3",
    "f12_eq_one",
    "aggregate_verify_commit",
    "multi_pairing_check",
    "pack_g1_points",
    "pack_g2_points",
    "unpack_g1_points",
    "unpack_g2_points",
]

BLS_X = host.BLS_X  # |x|; the parameter is negative

# -- Fp6 / Fp12 -------------------------------------------------------------


class F6(NamedTuple):
    c0: F2
    c1: F2
    c2: F2


class F12(NamedTuple):
    c0: F6
    c1: F6


F6_ZERO = F6(fp.F2_ZERO, fp.F2_ZERO, fp.F2_ZERO)
F6_ONE = F6(fp.F2_ONE, fp.F2_ZERO, fp.F2_ZERO)
F12_ONE = F12(F6_ONE, F6_ZERO)


def f6_add(a: F6, b: F6) -> F6:
    return F6(fp.f2_add(a.c0, b.c0), fp.f2_add(a.c1, b.c1), fp.f2_add(a.c2, b.c2))


def f6_sub(a: F6, b: F6) -> F6:
    return F6(fp.f2_sub(a.c0, b.c0), fp.f2_sub(a.c1, b.c1), fp.f2_sub(a.c2, b.c2))


def f6_neg(a: F6) -> F6:
    return F6(fp.f2_neg(a.c0), fp.f2_neg(a.c1), fp.f2_neg(a.c2))


def f6_renorm(a: F6) -> F6:
    return F6(
        fp.f2_renorm(a.c0), fp.f2_renorm(a.c1), fp.f2_renorm(a.c2)
    )


def f6_mul(a: F6, b: F6) -> F6:
    t0, t1, t2 = fp.f2_mul(a.c0, b.c0), fp.f2_mul(a.c1, b.c1), fp.f2_mul(a.c2, b.c2)
    c0 = fp.f2_add(
        t0,
        fp.f2_mul_xi(
            fp.f2_sub(
                fp.f2_mul(fp.f2_add(a.c1, a.c2), fp.f2_add(b.c1, b.c2)),
                fp.f2_add(t1, t2),
            )
        ),
    )
    c1 = fp.f2_add(
        fp.f2_sub(
            fp.f2_mul(fp.f2_add(a.c0, a.c1), fp.f2_add(b.c0, b.c1)),
            fp.f2_add(t0, t1),
        ),
        fp.f2_mul_xi(t2),
    )
    c2 = fp.f2_add(
        fp.f2_sub(
            fp.f2_mul(fp.f2_add(a.c0, a.c2), fp.f2_add(b.c0, b.c2)),
            fp.f2_add(t0, t2),
        ),
        t1,
    )
    return F6(c0, c1, c2)


def f6_mul_v(a: F6) -> F6:
    return F6(fp.f2_mul_xi(a.c2), a.c0, a.c1)


def f6_inv(a: F6) -> F6:
    # Callers hand in lazily-accumulated operands (f12_inv's f6_sub of
    # f6_mul outputs); renorm first so the squarings below stay inside the
    # REDC input range.
    a = f6_renorm(a)
    c0 = fp.f2_sub(fp.f2_sqr(a.c0), fp.f2_mul_xi(fp.f2_mul(a.c1, a.c2)))
    c1 = fp.f2_sub(fp.f2_mul_xi(fp.f2_sqr(a.c2)), fp.f2_mul(a.c0, a.c1))
    c2 = fp.f2_sub(fp.f2_sqr(a.c1), fp.f2_mul(a.c0, a.c2))
    t = fp.f2_add(
        fp.f2_mul(a.c0, c0),
        fp.f2_mul_xi(
            fp.f2_add(fp.f2_mul(a.c1, c2), fp.f2_mul(a.c2, c1))
        ),
    )
    tinv = fp.f2_inv(t)
    return F6(fp.f2_mul(c0, tinv), fp.f2_mul(c1, tinv), fp.f2_mul(c2, tinv))


def f12_mul(a: F12, b: F12) -> F12:
    t0 = f6_mul(a.c0, b.c0)
    t1 = f6_mul(a.c1, b.c1)
    c0 = f6_add(t0, f6_mul_v(t1))
    c1 = f6_sub(f6_mul(f6_add(a.c0, a.c1), f6_add(b.c0, b.c1)), f6_add(t0, t1))
    # Renorm outputs: keeps bounds flat across arbitrarily long chains
    # (Miller loop, final exp) so the FV trace-time asserts stay satisfied.
    return F12(f6_renorm(c0), f6_renorm(c1))


def f12_sqr(a: F12) -> F12:
    return f12_mul(a, a)


def f12_inv(a: F12) -> F12:
    t = f6_inv(f6_sub(f6_mul(a.c0, a.c0), f6_mul_v(f6_mul(a.c1, a.c1))))
    return F12(f6_renorm(f6_mul(a.c0, t)), f6_renorm(f6_neg(f6_mul(a.c1, t))))


def f12_renorm(a: F12) -> F12:
    return F12(f6_renorm(a.c0), f6_renorm(a.c1))


def f12_select(cond, a: F12, b: F12) -> F12:
    return jax.tree_util.tree_map(
        lambda x, y: fp.select(cond, x, y) if isinstance(x, FV) else x,
        a,
        b,
        is_leaf=lambda n: isinstance(n, FV),
    )


# -- Frobenius --------------------------------------------------------------
# w-basis: f = sum_k e_k w^k with e_0=c0.c0, e_1=c1.c0, e_2=c0.c1,
# e_3=c1.c1, e_4=c0.c2, e_5=c1.c2.  pi^n(f) = sum conj^n(e_k) gamma_{n,k} w^k
# with gamma_{n,k} = xi^(k (p^n - 1) / 6), computed with the host oracle's
# exact Fp2 arithmetic at import.


def _gamma(n: int):
    out = []
    for k in range(6):
        e = k * (host.P**n - 1) // 6
        acc = host.F2_ONE
        base = (1, 1)  # xi = 1 + u
        for bit in bin(e)[2:]:
            acc = host.f2_sqr(acc)
            if bit == "1":
                acc = host.f2_mul(acc, base)
        out.append(acc)
    return out


_GAMMA1 = _gamma(1)
_GAMMA2 = _gamma(2)
_GAMMA6 = _gamma(6)
# Conjugation f^(p^6) must be exactly c1-negation in this tower:
assert _GAMMA6[0] == (1, 0)
assert all(_GAMMA6[k] == ((1, 0) if k % 2 == 0 else (host.P - 1, 0)) for k in range(6))
# p^2 Frobenius coefficients are real (no conjugation):
assert all(g[1] == 0 for g in _GAMMA2)


def _gamma_const(g) -> F2:
    return fp.f2_const(g[0], g[1])


def f12_conj(a: F12) -> F12:
    """f^(p^6): negate the odd w-powers (verified against _GAMMA6 above)."""
    return F12(a.c0, f6_neg(a.c1))


def _ek(a: F12, k: int) -> F2:
    six = [a.c0.c0, a.c1.c0, a.c0.c1, a.c1.c1, a.c0.c2, a.c1.c2]
    return six[k]


def _from_ek(e) -> F12:
    return F12(F6(e[0], e[2], e[4]), F6(e[1], e[3], e[5]))


def f12_frob(a: F12, n: int) -> F12:
    gam = {1: _GAMMA1, 2: _GAMMA2}[n]
    es = []
    for k in range(6):
        e = _ek(a, k)
        if n % 2 == 1:
            e = fp.f2_conj(e)
        es.append(fp.f2_mul(e, _gamma_const(gam[k])))
    return _from_ek(es)


# -- fixed-exponent Fp12 powers (scan over public bits) ---------------------


def _f12_arrs(a: F12):
    return [v.arr for v in jax.tree_util.tree_leaves(a, is_leaf=lambda n: isinstance(n, FV))]


def _f12_from_arrs(arrs, template: F12) -> F12:
    leaves = jax.tree_util.tree_leaves(template, is_leaf=lambda n: isinstance(n, FV))
    rebuilt = [FV(arr, RN_BOUND) for arr, _ in zip(arrs, leaves)]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template, is_leaf=lambda n: isinstance(n, FV)),
        rebuilt,
    )


def f12_pow_fixed(a: F12, exponent: int) -> F12:
    """a**exponent (public exponent) via an MSB-first square-and-multiply
    scan; operand arrays are carried raw and rewrapped with the static
    RN_BOUND each step (FV bounds cannot ride a scan carrier)."""
    assert exponent > 0
    a = f12_renorm(a)
    nbits = exponent.bit_length()
    bits = jnp.asarray(
        [(exponent >> i) & 1 for i in range(nbits - 2, -1, -1)], dtype=bool
    )

    def body(arrs, bit):
        acc = _f12_from_arrs(arrs, a)
        acc = f12_sqr(acc)
        withm = f12_mul(acc, a)
        sel = jax.tree_util.tree_map(
            lambda x, y: fp.select(
                jnp.broadcast_to(bit, x.arr.shape[:-1]), y, x
            ),
            acc,
            withm,
            is_leaf=lambda n: isinstance(n, FV),
        )
        return _f12_arrs(sel), None

    out, _ = jax.lax.scan(body, _f12_arrs(a), bits)
    return _f12_from_arrs(out, a)


def exp_by_neg_x(a: F12) -> F12:
    """a^x for the (negative) curve parameter, valid in the cyclotomic
    subgroup where inversion is conjugation."""
    return f12_conj(f12_pow_fixed(a, BLS_X))


# -- G1 (Fp) and G2 (Fp2) Jacobian ops --------------------------------------


class G1Jac(NamedTuple):
    x: FV
    y: FV
    z: FV


class G2Jac(NamedTuple):
    x: F2
    y: F2
    z: F2


def _jac_ops(F):
    """Field-generic complete Jacobian double/add (a = 0 curves), the
    branchless-select structure proven in ops/secp256k1.py."""

    def double(p):
        a = F.sqr(p.x)
        b = F.sqr(p.y)
        c = F.sqr(b)
        t = F.sqr(F.add(p.x, b))
        # d and x3 are renormed before feeding the y3 product: the lazy
        # sub-chains fatten their bounds past the REDC input range otherwise
        # (the bound algebra is asserted at trace time in bls_fp._redc).
        d = F.renorm(F.muli(F.sub(F.sub(t, a), c), 2))
        e = F.muli(a, 3)
        ff = F.sqr(e)
        x3 = F.renorm(F.sub(ff, F.muli(d, 2)))
        y3 = F.sub(F.mul(e, F.sub(d, x3)), F.muli(c, 8))
        z3 = F.muli(F.mul(p.y, p.z), 2)
        return type(p)(x3, F.renorm(y3), F.renorm(z3))

    def add_complete(p, q):
        z1s = F.sqr(p.z)
        z2s = F.sqr(q.z)
        u1 = F.mul(p.x, z2s)
        u2 = F.mul(q.x, z1s)
        s1 = F.mul(p.y, F.mul(z2s, q.z))
        s2 = F.mul(q.y, F.mul(z1s, p.z))
        # Renormed: h and r feed long mul chains below (bound hygiene).
        h = F.renorm(F.sub(u2, u1))
        r = F.renorm(F.sub(s2, s1))
        hs = F.sqr(h)
        hc = F.mul(hs, h)
        u1hs = F.mul(u1, hs)
        x3 = F.sub(F.sub(F.sqr(r), hc), F.muli(u1hs, 2))
        y3 = F.sub(F.mul(r, F.sub(u1hs, x3)), F.mul(s1, hc))
        z3 = F.mul(F.mul(p.z, q.z), h)
        generic = type(p)(F.renorm(x3), F.renorm(y3), F.renorm(z3))

        same_x = F.is_zero(h)
        same_y = F.is_zero(r)
        dbl = double(p)
        out = _sel_pt(F, same_x & same_y, dbl, generic)
        out = _sel_pt(F, F.is_zero(p.z), q, out)
        out = _sel_pt(F, F.is_zero(q.z), p, out)
        return out

    return double, add_complete


def _sel_pt(F, cond, a, b):
    return type(a)(
        F.sel(cond, a.x, b.x), F.sel(cond, a.y, b.y), F.sel(cond, a.z, b.z)
    )


class _FpOps:
    add = staticmethod(fp.add)
    sub = staticmethod(fp.sub)
    mul = staticmethod(fp.mul)
    muli = staticmethod(fp.muli)
    sel = staticmethod(fp.select)
    renorm = staticmethod(fp.renorm_to)

    @staticmethod
    def sqr(a):
        return fp.mul(a, a)

    @staticmethod
    def is_zero(a):
        return fp.is_zero(fp.renorm(a) if a.bound > 8 * P else a)


class _Fp2Ops:
    add = staticmethod(fp.f2_add)
    sub = staticmethod(fp.f2_sub)
    mul = staticmethod(fp.f2_mul)
    muli = staticmethod(fp.f2_muli)
    sel = staticmethod(fp.f2_select)
    is_zero = staticmethod(fp.f2_is_zero)

    @staticmethod
    def sqr(a):
        return fp.f2_sqr(a)

    @staticmethod
    def renorm(a):
        return F2(fp.renorm_to(a.c0), fp.renorm_to(a.c1))


_g1_double, _g1_add = _jac_ops(_FpOps)
_g2_double, _g2_add = _jac_ops(_Fp2Ops)


def _tree_reduce(points, point_add, n: int):
    """Log-depth masked sum folding the point axis (second-to-last array
    axis), as ONE ``lax.scan`` over the halving levels.

    The scan body holds a SINGLE point-add instance where the previous
    unrolled form inlined ``log2(n)`` of them — at the 8-validator pin
    that alone was three complete-add traces per group, most of the
    aggregation stage's stablehlo (the same dedup discipline as the
    hard-part chain's five-exp scan).  Each level ``k`` computes
    ``points[i] + points[i + n/2^(k+1)]`` over the FULL fixed-shape axis
    (a dynamic roll keeps the scan carrier shape-invariant); lanes at or
    past the live half become garbage that no later level — and not the
    final ``[..., 0, :]`` read — ever consumes, so no per-level mask is
    needed.  Coordinates are renormed to the fixed :data:`~.bls_fp.
    RN_BOUND` up front so the carried bounds are step-invariant.

    Leading batch axes are supported: ``(..., V, L)`` limb arrays reduce
    ``V`` groups-parallel (the multi-pairing route's per-lane pubkey
    aggregation rides this).
    """
    assert n and (n & (n - 1)) == 0, "pad validator axis to a power of two"

    def fvmap(fn, tree):
        return jax.tree_util.tree_map(
            lambda v: FV(fn(v.arr), v.bound),
            tree,
            is_leaf=lambda x: isinstance(x, FV),
        )

    points = jax.tree_util.tree_map(
        fp.renorm_to, points, is_leaf=lambda x: isinstance(x, FV)
    )
    if n == 1:
        return fvmap(lambda a: a[..., 0, :], points)

    def arrs(tree):
        return [
            v.arr
            for v in jax.tree_util.tree_leaves(
                tree, is_leaf=lambda x: isinstance(x, FV)
            )
        ]

    def rebuild(raw):
        rebuilt = [FV(a, RN_BOUND) for a in raw]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(
                points, is_leaf=lambda x: isinstance(x, FV)
            ),
            rebuilt,
        )

    halves = jnp.asarray(
        [n >> (k + 1) for k in range(n.bit_length() - 1)], dtype=jnp.int32
    )

    def body(carry, half):
        pts = rebuild(carry)
        shifted = fvmap(lambda a: jnp.roll(a, -half, axis=-2), pts)
        return arrs(point_add(pts, shifted)), None

    out, _ = jax.lax.scan(body, arrs(points), halves)
    return fvmap(lambda a: a[..., 0, :], rebuild(out))


def g1_aggregate(xs: FV, ys: FV, live) -> G1Jac:
    """Masked sum of affine G1 points over the point axis (power of 2).

    ``xs``/``ys`` are ``(..., V, L)`` limb arrays, ``live`` is ``(..., V)``;
    leading axes batch independent groups through one shared tree."""
    n = xs.arr.shape[-2]
    one = FV(jnp.broadcast_to(jnp.asarray(fp.ONE.arr), xs.arr.shape), fp.ONE.bound)
    z = fp.select(live, one, FV(jnp.zeros_like(xs.arr), 1))
    pts = G1Jac(xs, ys, z)
    return _tree_reduce(pts, _g1_add, n)


def g2_aggregate(xs: F2, ys: F2, live) -> G2Jac:
    n = xs.c0.arr.shape[-2]
    one_arr = jnp.broadcast_to(jnp.asarray(fp.ONE.arr), xs.c0.arr.shape)
    zero_arr = jnp.zeros_like(xs.c0.arr)
    z = F2(
        fp.select(live, FV(one_arr, fp.ONE.bound), FV(zero_arr, 1)),
        FV(zero_arr, 1),
    )
    pts = G2Jac(xs, ys, z)
    return _tree_reduce(pts, _g2_add, n)


def jac_to_affine_g1(p: G1Jac) -> Tuple[FV, FV]:
    zinv = fp.inv(fp.renorm(p.z))
    zi2 = fp.mul(zinv, zinv)
    return fp.mul(p.x, zi2), fp.mul(p.y, fp.mul(zi2, zinv))


def jac_to_affine_g2(p: G2Jac) -> Tuple[F2, F2]:
    zinv = fp.f2_inv(_Fp2Ops.renorm(p.z))
    zi2 = fp.f2_sqr(zinv)
    return fp.f2_mul(p.x, zi2), fp.f2_mul(p.y, fp.f2_mul(zi2, zinv))


# -- Miller loop ------------------------------------------------------------


def _sparse_line(e0: F2, e3: F2, e5: F2) -> F12:
    """Line element in the w-basis slots (0, 3, 5) — see the derivation in
    the module docstring of how the M-twist untwisting lands there."""
    zero_like = F2(
        FV(jnp.zeros_like(e0.c0.arr), 1), FV(jnp.zeros_like(e0.c0.arr), 1)
    )
    return _from_ek([e0, zero_like, zero_like, e3, zero_like, e5])


def _dbl_step(T: G2Jac, xP: FV, yP: FV):
    """Tangent line at T evaluated at P, plus 2T.

    Line (scaled by the subfield factor 2*Y*Z^3*xi, legal under final exp):
    e0 = -2 yP xi Y Z^3, e3 = 2 Y^2 - 3 X^3, e5 = 3 xP X^2 Z^2.
    """
    X, Y, Z = T.x, T.y, T.z
    z2 = fp.f2_sqr(Z)
    z3 = fp.f2_mul(z2, Z)
    yz3 = fp.f2_mul(Y, z3)
    e0 = fp.f2_neg(fp.f2_muli(fp.f2_mul_xi(_f2_mul_fp(yz3, yP)), 2))
    y2 = fp.f2_sqr(Y)
    x2 = fp.f2_sqr(X)
    x3 = fp.f2_mul(x2, X)
    e3 = fp.f2_sub(fp.f2_muli(y2, 2), fp.f2_muli(x3, 3))
    e5 = fp.f2_muli(_f2_mul_fp(fp.f2_mul(x2, z2), xP), 3)
    return _sparse_line(e0, e3, e5), _g2_double(T)


def _add_step(T: G2Jac, qx: F2, qy: F2, xP: FV, yP: FV):
    """Line through T and the affine twist point Q, evaluated at P; plus
    T + Q (mixed).

    With H = xQ Z^2 - X, r = yQ Z^3 - Y, the chord line scaled by Z*H and
    mapped into the w-basis via the same ``* w^3`` trick as the doubling
    line (w^6 = xi), overall sign -1 (legal: -1 is in Fp, killed by the
    final exponentiation):
    e0 = -xi Z H yP,  e3 = yQ Z H - r xQ,  e5 = xP r.
    (The e3 sign is load-bearing: r xQ - yQ Z H breaks the Fp2-ratio
    invariant against the untwisted chord and fails the pairing; see
    tests/test_bls_device.py::test_pairing_matches_host_cubed.)
    """
    X, Y, Z = T.x, T.y, T.z
    z2 = fp.f2_sqr(Z)
    z3 = fp.f2_mul(z2, Z)
    H = fp.f2_sub(fp.f2_mul(qx, z2), X)
    r = fp.f2_sub(fp.f2_mul(qy, z3), Y)
    zh = fp.f2_mul(Z, H)
    e0 = fp.f2_neg(fp.f2_mul_xi(_f2_mul_fp(zh, yP)))
    e3 = fp.f2_sub(fp.f2_mul(qy, zh), fp.f2_mul(r, qx))
    e5 = _f2_mul_fp(r, xP)

    hs = fp.f2_sqr(H)
    hc = fp.f2_mul(hs, H)
    v = fp.f2_mul(X, hs)
    x3 = fp.f2_sub(fp.f2_sub(fp.f2_sqr(r), hc), fp.f2_muli(v, 2))
    y3 = fp.f2_sub(fp.f2_mul(r, fp.f2_sub(v, x3)), fp.f2_mul(Y, hc))
    z3n = fp.f2_mul(Z, H)
    Tn = G2Jac(
        _Fp2Ops.renorm(x3), _Fp2Ops.renorm(y3), _Fp2Ops.renorm(z3n)
    )
    return _sparse_line(e0, e3, e5), Tn


def _f2_mul_fp(a: F2, s: FV) -> F2:
    return F2(fp.mul(a.c0, s), fp.mul(a.c1, s))


_X_BITS = [int(b) for b in bin(BLS_X)[3:]]  # MSB-first, skip leading 1


def miller_loop(qx: F2, qy: F2, px: FV, py: FV) -> F12:
    """f_{|x|, Q}(P), conjugated for the negative parameter.

    One scan over the 63 remaining bits of |x|: every step computes the
    doubling line; add-steps are computed branchlessly and selected in on
    the 6 set bits.

    Batched over leading axes: several independent pairings ride ONE scan
    (the staged :func:`aggregate_verify_commit` runs both sides of the
    verification equation as a 2-lane batch — one compiled body where two
    sequential Miller programs would double both compile size and runtime).
    """
    qx = _Fp2Ops.renorm(qx)
    qy = _Fp2Ops.renorm(qy)
    px = fp.renorm_to(px)
    py = fp.renorm_to(py)
    # Scan carriers must have the batched shape from step 0: broadcast the
    # accumulator and the constant Z/ONE limbs up to the input batch.
    batch = jnp.broadcast_shapes(qx.c0.arr.shape[:-1], px.arr.shape[:-1])

    def bcast(v: FV) -> FV:
        return FV(jnp.broadcast_to(v.arr, batch + v.arr.shape[-1:]), v.bound)

    T0 = jax.tree_util.tree_map(
        bcast,
        G2Jac(qx, qy, F2(fp.renorm_to(fp.ONE), fp.renorm_to(fp.ZERO))),
        is_leaf=lambda n: isinstance(n, FV),
    )
    f0 = jax.tree_util.tree_map(
        bcast, f12_renorm(F12_ONE), is_leaf=lambda n: isinstance(n, FV)
    )
    bits = jnp.asarray(_X_BITS, dtype=bool)

    def arrs(tree):
        return [
            v.arr
            for v in jax.tree_util.tree_leaves(
                tree, is_leaf=lambda n: isinstance(n, FV)
            )
        ]

    def rebuild(raw, template):
        leaves = jax.tree_util.tree_leaves(
            template, is_leaf=lambda n: isinstance(n, FV)
        )
        rebuilt = [FV(a, RN_BOUND) for a, _ in zip(raw, leaves)]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(
                template, is_leaf=lambda n: isinstance(n, FV)
            ),
            rebuilt,
        )

    state0 = (arrs(T0), arrs(f0))

    def body(state, bit):
        t_raw, f_raw = state
        T = rebuild(t_raw, T0)
        f = rebuild(f_raw, f0)
        line_d, T2 = _dbl_step(T, px, py)
        f2_ = f12_mul(f12_sqr(f), f12_renorm(line_d))
        line_a, T3 = _add_step(T2, qx, qy, px, py)
        f3_ = f12_mul(f2_, f12_renorm(line_a))
        cond = jnp.asarray(bit)
        Tn = _sel_pt(_Fp2Ops, jnp.broadcast_to(cond, ()), T3, T2)
        Tn = G2Jac(
            _Fp2Ops.renorm(Tn.x), _Fp2Ops.renorm(Tn.y), _Fp2Ops.renorm(Tn.z)
        )
        fn = f12_select(jnp.broadcast_to(cond, ()), f3_, f2_)
        fn = f12_renorm(fn)
        return (arrs(Tn), arrs(fn)), None

    state, _ = jax.lax.scan(body, state0, bits)
    f = rebuild(state[1], f0)
    return f12_conj(f)  # negative parameter


# -- final exponentiation (cubed variant) -----------------------------------

# Identity check: the 2020/875 chain computes f^(3*(p^4-p^2+1)/r).
assert (BLS_X + 1) ** 2 * (-BLS_X + host.P) * (
    BLS_X**2 + host.P**2 - 1
) + 3 == 3 * ((host.P**4 - host.P**2 + 1) // host.R), "hard-part chain"
# note: x = -BLS_X, so (x-1)^2 == (BLS_X+1)^2 and (x+p) == (p - BLS_X).


def final_exp3(f: F12) -> F12:
    """f^(3 * (p^12 - 1) / r): easy part then the 2020/875 chain.

    The extra factor 3 (vs the canonical final exp) is a bijection on the
    r-order target group (gcd(3, r) = 1), so equality checks are
    unaffected; tests against the host compare ``host_result**3``.
    """
    # easy: f^((p^6 - 1)(p^2 + 1))
    f = f12_mul(f12_conj(f), f12_inv(f))
    f = f12_mul(f12_frob(f, 2), f)

    # hard (cyclotomic from here): ((f^(x-1))^(x-1))^(x+p)^(x^2+p^2-1) * f^3
    def exp_x_minus_1(g: F12) -> F12:
        return f12_mul(exp_by_neg_x(g), f12_conj(g))

    t = exp_x_minus_1(exp_x_minus_1(f))
    t = f12_mul(exp_by_neg_x(t), f12_frob(t, 1))  # ^(x + p)
    t2 = exp_by_neg_x(exp_by_neg_x(t))  # ^(x^2)
    t = f12_mul(f12_mul(t2, f12_frob(t, 2)), f12_conj(t))  # ^(x^2 + p^2 - 1)
    f3 = f12_mul(f12_sqr(f), f)
    return f12_renorm(f12_mul(t, f3))


def f12_eq_one(f: F12) -> jnp.ndarray:
    """f == 1 exactly (canonical comparison at the edges).

    The 12 tower components canonicalize in ONE stacked
    :func:`~go_ibft_tpu.ops.bls_fp.canon_mod_p` call (axis -2 is the
    component) instead of 12 separate instantiations — the sequential
    carry/peel chain inside canon is most of the finish stage's trace,
    and stacking dedups it 12-to-1 (same scan/dedup discipline as the
    hard-part stage; semantics unchanged)."""
    comps = []
    for k in range(6):
        e = _ek(f, k)
        comps.append(fp.renorm(e.c0))
        comps.append(fp.renorm(e.c1))
    stacked = fp.FV(
        jnp.stack([c.arr for c in comps], axis=-2),
        max(c.bound for c in comps),
    )
    canon = fp.canon_mod_p(stacked)  # (..., 12, L)
    ref = jnp.zeros_like(canon)
    ref = ref.at[..., 0, :].set(jnp.asarray(fp.to_mont(1).arr))
    return jnp.all(canon == ref, axis=(-2, -1))


# -- host packing + the aggregate kernel ------------------------------------


def pack_g1_points(points) -> Tuple[np.ndarray, np.ndarray]:
    """Affine G1 points -> Montgomery limb arrays (infinity -> (0, 0))."""
    xs = [0 if p is None else p[0] for p in points]
    ys = [0 if p is None else p[1] for p in points]
    return fp.pack_mont(xs), fp.pack_mont(ys)


def pack_g2_points(points):
    """Affine G2 points -> 4 Montgomery limb arrays (x0, x1, y0, y1)."""
    x0 = [0 if p is None else p[0][0] for p in points]
    x1 = [0 if p is None else p[0][1] for p in points]
    y0 = [0 if p is None else p[1][0] for p in points]
    y1 = [0 if p is None else p[1][1] for p in points]
    return (
        fp.pack_mont(x0),
        fp.pack_mont(x1),
        fp.pack_mont(y0),
        fp.pack_mont(y1),
    )


_G1_GEN_X = fp.pack_mont([host.G1_GEN[0]])[0]
_G1_GEN_Y = fp.pack_mont([host.G1_GEN[1]])[0]


# The verification equation runs as a PIPELINE of moderate-size compiled
# programs instead of one monolith.  Two reasons, both structural:
#
# * compile robustness: the single fused program (2 Miller scans + 5
#   exp-by-x scans + inversions) is large enough to OOM-kill constrained
#   XLA compile services; each stage below is a fraction of that, and the
#   exp-by-x kernel — the bulk of the final exponentiation — is compiled
#   ONCE and dispatched five times;
# * less work: the pairing ratio uses e(G1, S) * e(-PK, H) == 1 (negating
#   the G1 argument is one field negation), which deletes the Fp12
#   inversion of the old ``m1 * m2^-1`` form, and both Miller loops ride
#   one 2-lane batched scan (see :func:`miller_loop`).


def _f12_renorm_to(a: F12) -> F12:
    """Renorm every leaf to the fixed RN_BOUND — the stage-boundary form."""
    return jax.tree_util.tree_map(
        fp.renorm_to, a, is_leaf=lambda n: isinstance(n, FV)
    )


@jax.jit
def _aggregate_stage(pk_x, pk_y, sig_x0, sig_x1, sig_y0, sig_y1, live):
    """Masked tree aggregation + affine conversion (one dispatch).

    Returns the affine aggregates with the G1 y-coordinate NEGATED (the
    pairing-ratio trick) plus the nonempty flag, all renormed to RN_BOUND.
    """
    bnd = P  # host packs canonical (< p) values

    def fv(a):
        return FV(a, bnd)

    pk_agg = g1_aggregate(fv(pk_x), fv(pk_y), live)
    sig_agg = g2_aggregate(
        F2(fv(sig_x0), fv(sig_x1)), F2(fv(sig_y0), fv(sig_y1)), live
    )
    nonempty = ~fp.is_zero(fp.renorm(pk_agg.z)) & ~fp.f2_is_zero(sig_agg.z)
    pk_ax, pk_ay = jac_to_affine_g1(pk_agg)
    sig_ax, sig_ay = jac_to_affine_g2(sig_agg)
    return (
        fp.renorm_to(pk_ax).arr,
        fp.renorm_to(fp.neg(pk_ay)).arr,
        fp.renorm_to(sig_ax.c0).arr,
        fp.renorm_to(sig_ax.c1).arr,
        fp.renorm_to(sig_ay.c0).arr,
        fp.renorm_to(sig_ay.c1).arr,
        nonempty,
    )


@jax.jit
def _miller_product_stage(qx0, qx1, qy0, qy1, px, py):
    """Both pairings' Miller loops as ONE 2-lane batched scan, then their
    F12 product (the ratio, thanks to the negated G1 lane)."""

    def rn(a):
        return FV(a, RN_BOUND)

    f = miller_loop(F2(rn(qx0), rn(qx1)), F2(rn(qy0), rn(qy1)), rn(px), rn(py))

    def lane(i):
        return jax.tree_util.tree_map(
            lambda v: FV(v.arr[i], v.bound),
            f,
            is_leaf=lambda n: isinstance(n, FV),
        )

    return _f12_arrs(_f12_renorm_to(f12_mul(lane(0), lane(1))))


@jax.jit
def _easy_part_stage(arrs):
    """f^((p^6 - 1)(p^2 + 1)) — the final exponentiation's easy part."""
    f = _f12_from_arrs(arrs, F12_ONE)
    g = f12_mul(f12_conj(f), f12_inv(f))
    g = f12_mul(f12_frob(g, 2), g)
    return _f12_arrs(_f12_renorm_to(g))


def _f12_select(cond, a: F12, b: F12) -> F12:
    """Branchless tree select (cond -> a); bounds follow fp.select."""
    return jax.tree_util.tree_map(
        lambda x, y: fp.select(
            jnp.broadcast_to(cond, x.arr.shape[:-1]), x, y
        ),
        a,
        b,
        is_leaf=lambda n: isinstance(n, FV),
    )


# Per-step combine mode for the hard-part chain's five exp-by-x steps:
# 0 = multiply by conj(cur), 1 = multiply by frob(cur, 1), 2 = take the
# exp output alone.  Step 2's result is the chain's t (saved for the
# finish stage); the final carry is t2 = t^(x^2).
_HARD_PART_MODE = (0, 0, 1, 2, 2)
_HARD_PART_SAVE = (False, False, True, False, False)


@jax.jit
def _hard_part_stage(f_arrs):
    """The 2020/875 hard-part chain as ONE five-step scan.

    Mathematically identical to the old five separate exp dispatches —

        t  = exp(f) * conj(f)        # f^(x-1)
        t  = exp(t) * conj(t)        # ^(x-1)
        t  = exp(t) * frob(t, 1)     # ^(x+p)       (saved)
        t2 = exp(exp(t))             # ^(x^2)

    — but the exp-by-x body (the bulk of the final exponentiation's
    trace) appears ONCE instead of five times when the whole pipeline is
    lowered as a single program (scripts/compile_budget.py pins exactly
    that form: five inlined exp scans were most of the 414k-line
    ``bls_aggregate_verify_8v`` trace).  The inter-step combines run
    branchlessly: every step computes ``exp(cur) * sel(conj(cur) |
    frob(cur,1))`` and selects between the product and the bare exp
    output by the step's mode — two F12 muls of slack per verification
    against four fewer copies of the exp trace.  Returns
    ``(t2_arrs, t_arrs)`` for :func:`_finish_stage`.
    """
    mode = jnp.asarray(_HARD_PART_MODE, dtype=jnp.int32)
    save = jnp.asarray(_HARD_PART_SAVE)

    def body(carry, xs):
        cur_arrs, saved_arrs = carry
        m, sv = xs
        cur = _f12_from_arrs(cur_arrs, F12_ONE)
        e = exp_by_neg_x(cur)
        operand = _f12_select(
            m == 0,
            _f12_renorm_to(f12_conj(cur)),
            _f12_renorm_to(f12_frob(cur, 1)),
        )
        prod = _f12_renorm_to(f12_mul(e, operand))
        nxt = _f12_select(m == 2, _f12_renorm_to(e), prod)
        saved = _f12_select(
            sv, nxt, _f12_from_arrs(saved_arrs, F12_ONE)
        )
        return (_f12_arrs(nxt), _f12_arrs(saved)), None

    (t2_arrs, t_arrs), _ = jax.lax.scan(
        body, (list(f_arrs), list(f_arrs)), (mode, save)
    )
    return t2_arrs, t_arrs


def _f12_cast_rn(a: F12) -> F12:
    """Re-tag (or renorm when genuinely fat) every leaf to RN_BOUND —
    the cheap stage-boundary cast for leaves already inside the bound."""
    return jax.tree_util.tree_map(
        lambda v: FV(v.arr, RN_BOUND)
        if v.bound <= RN_BOUND
        else fp.renorm_to(v),
        a,
        is_leaf=lambda n: isinstance(n, FV),
    )


@jax.jit
def _finish_stage(t2_arrs, t_arrs, f_arrs, nonempty):
    """t2 * frob(t,2) * conj(t) * f^3 == 1, gated on nonempty.

    The five-way product runs as ONE scanned multiply over the stacked
    factors [frob(t,2), conj(t), f, f, f] (f^3 = three f factors —
    exact field value, order-independent) with carry t2: one f12_mul
    trace instead of five inlined ones, the same dedup discipline as
    :func:`_hard_part_stage` (five f12_muls were most of this stage's
    ~90k stablehlo lines).
    """
    t2 = _f12_from_arrs(t2_arrs, F12_ONE)
    t = _f12_from_arrs(t_arrs, F12_ONE)
    f = _f12_from_arrs(f_arrs, F12_ONE)
    factors = [
        _f12_cast_rn(f12_frob(t, 2)),
        _f12_cast_rn(f12_conj(t)),
        f,
        f,
        f,
    ]
    xs = [
        jnp.stack(leaves)
        for leaves in zip(*(_f12_arrs(fac) for fac in factors))
    ]

    def body(acc_arrs, factor_arrs):
        acc = _f12_from_arrs(acc_arrs, F12_ONE)
        fac = _f12_from_arrs(factor_arrs, F12_ONE)
        return _f12_arrs(_f12_renorm_to(f12_mul(acc, fac))), None

    out, _ = jax.lax.scan(body, list(t2_arrs), xs)
    return f12_eq_one(_f12_from_arrs(out, F12_ONE)) & nonempty


# Cost-ledger compile watch (ISSUE 14): the staged jit objects behind the
# pairing entry points, named for compile_ledger.jsonl.  Kernel identity
# is attributed HERE (where the jit objects live) so every consumer route
# — certifier, block-sync, serve, bench — shares one compile record per
# program; the final-exp stages appear in both tuples because they are
# the SAME jit objects (the reuse the compile budget pins), so whichever
# entry point runs first pays — and records — the compile.
_PAIRING_KERNELS = (
    ("bls_aggregate_stage", _aggregate_stage),
    ("bls_miller_product", _miller_product_stage),
    ("bls_finalexp_easy", _easy_part_stage),
    ("bls_finalexp_hard", _hard_part_stage),
    ("bls_finalexp_finish", _finish_stage),
)


def aggregate_verify_commit(
    pk_x,
    pk_y,
    sig_x0,
    sig_x1,
    sig_y0,
    sig_y1,
    h_x0,
    h_x1,
    h_y0,
    h_y1,
    live,
):
    """Device aggregate COMMIT verification.

    ``e(G1, sum(sig_i)) == e(sum(pk_i), H2(m))`` over the live lanes,
    checked as ``final_exp(e(G1, S) * e(-PK, H)) == 1``.  Inputs:
    per-validator G1 pubkeys ``(V, L)``, per-validator G2 seal points
    ``(V, L)`` x4 components, the message point H2(m) ``(L,)`` x4, and the
    live mask ``(V,)`` (V a power of two).  Returns a scalar bool array.

    Dispatches the staged pipeline above: aggregation, one batched Miller
    scan, then the final exponentiation as easy-part + the hard-part
    chain scanned over its five exp-by-x steps (ONE compiled trace of the
    exp body instead of five — see :func:`_hard_part_stage`).  Semantics
    are identical to the fused form (same tower, same hard-part chain —
    see :func:`final_exp3`); only the dispatch granularity differs.
    """
    with cost_ledger.dispatch_span(
        "bls_aggregate_verify",
        route="device",
        live_mask=live,
        kernels=_PAIRING_KERNELS,
        block=False,
        site="ops/bls12_381.py:aggregate_verify_commit",
    ):
        (pk_ax, npk_ay, sx0, sx1, sy0, sy1, nonempty) = _aggregate_stage(
            pk_x, pk_y, sig_x0, sig_x1, sig_y0, sig_y1, live
        )
        # Lane 0: Q = sum(sig) with P = G1 generator; lane 1: Q = H2(m)
        # with P = -sum(pk).
        prod = _miller_product_stage(
            jnp.stack([sx0, jnp.asarray(h_x0)]),
            jnp.stack([sx1, jnp.asarray(h_x1)]),
            jnp.stack([sy0, jnp.asarray(h_y0)]),
            jnp.stack([sy1, jnp.asarray(h_y1)]),
            jnp.stack([jnp.asarray(_G1_GEN_X), pk_ax]),
            jnp.stack([jnp.asarray(_G1_GEN_Y), npk_ay]),
        )
        f = _easy_part_stage(prod)
        t2, t = _hard_part_stage(f)
        return _finish_stage(t2, t, f, nonempty)


# -- device merge trees (ISSUE 12) ------------------------------------------
# The standalone aggregation kernels: the same scanned masked tree the
# pairing pipeline uses, exposed as its own dispatch so host consumers
# (BLSCertifier.build, the aggregation-tree pump, verify/aggregate.py's
# drop-in aggregate_signatures/aggregate_pubkeys) can merge WITHOUT paying
# a pairing.  Outputs are CANONICAL Montgomery limbs (one stacked
# canon_mod_p for all components — the f12_eq_one dedup discipline) so the
# host unpackers recover exact integers.  Leading batch axes merge many
# disjoint groups in one dispatch (the tree-gossip pump's per-sweep
# combine).


def _stacked_canon(comps):
    """Canonicalize N same-shape FVs through ONE canon_mod_p call."""
    stacked = FV(
        jnp.stack([c.arr for c in comps], axis=-2),
        max(c.bound for c in comps),
    )
    return fp.canon_mod_p(stacked)  # (..., N, L)


@jax.jit
def g2_merge_tree(sx0, sx1, sy0, sy1, live):
    """Masked G2 merge tree -> canonical affine limbs + infinity flag.

    Inputs: ``(..., V, L)`` packed canonical Montgomery limbs (V a power
    of two) and a ``(..., V)`` live mask.  Returns ``(..., 4, L)``
    canonical limbs (x0, x1, y0, y1 — Montgomery domain, < p) and a
    ``(...,)`` bool that is True when the masked sum is the point at
    infinity (the affine limbs are then meaningless zeros).
    """
    bnd = P

    def fv(a):
        return FV(a, bnd)

    agg = g2_aggregate(F2(fv(sx0), fv(sx1)), F2(fv(sy0), fv(sy1)), live)
    inf = fp.f2_is_zero(agg.z)
    ax, ay = jac_to_affine_g2(agg)
    return _stacked_canon([ax.c0, ax.c1, ay.c0, ay.c1]), inf


@jax.jit
def g1_merge_tree(px, py, live):
    """Masked G1 merge tree -> canonical affine limbs + infinity flag.

    Same contract as :func:`g2_merge_tree` with ``(..., 2, L)`` (x, y)
    canonical output limbs."""
    agg = g1_aggregate(FV(px, P), FV(py, P), live)
    inf = fp.is_zero(fp.renorm(agg.z))
    ax, ay = jac_to_affine_g1(agg)
    return _stacked_canon([ax, ay]), inf


def unpack_g1_points(limbs, inf) -> list:
    """Host unpacking: ``(..., 2, L)`` canonical Montgomery limbs (+ the
    infinity flags) -> affine host points (None for infinity)."""
    limbs = np.asarray(limbs).reshape(-1, 2, limbs.shape[-1])
    flags = np.asarray(inf).reshape(-1)
    out = []
    for row, is_inf in zip(limbs, flags):
        if bool(is_inf):
            out.append(None)
            continue
        x, y = fp.from_mont_limbs(row)
        out.append((x, y))
    return out


def unpack_g2_points(limbs, inf) -> list:
    """Host unpacking: ``(..., 4, L)`` canonical limbs -> G2 host points."""
    limbs = np.asarray(limbs).reshape(-1, 4, limbs.shape[-1])
    flags = np.asarray(inf).reshape(-1)
    out = []
    for row, is_inf in zip(limbs, flags):
        if bool(is_inf):
            out.append(None)
            continue
        x0, x1, y0, y1 = fp.from_mont_limbs(row)
        out.append(((x0, x1), (y0, y1)))
    return out


# -- batched multi-pairing (ISSUE 12) ---------------------------------------
# MANY certificates in ONE staged dispatch: per lane i the equation is
# e(G1, S_i) == e(PK_i, H_i), checked as final_exp(e(G1, S_i) *
# e(-PK_i, H_i)) == 1.  All 2N Miller loops ride ONE batched scan (the
# (2, N) leading shape — side-major so lane 0 of the single-cert program
# is literally the N=1 case), and the final exponentiation reuses the
# SAME staged jit objects (_easy_part_stage / _hard_part_stage /
# _finish_stage) the single-certificate pipeline compiled — batching adds
# exactly ONE new program family (the per-lane pubkey aggregation + the
# miller product), which scripts/compile_budget.py pins.


@jax.jit
def _multi_g1_neg_aggregate_stage(pk_x, pk_y, live):
    """Per-lane pubkey aggregation for the multi-pairing pipeline.

    ``(N, V, L)`` packed pubkey limbs + ``(N, V)`` live mask -> the
    NEGATED affine aggregate per lane (renormed Montgomery limbs, the
    pairing-ratio form) plus the per-lane nonempty flag.  One scanned
    tree serves every lane (leading-axis batching of :func:`g1_aggregate`).
    """
    agg = g1_aggregate(FV(pk_x, P), FV(pk_y, P), live)
    nonempty = ~fp.is_zero(fp.renorm(agg.z))
    ax, ay = jac_to_affine_g1(agg)
    return (
        fp.renorm_to(ax).arr,
        fp.renorm_to(fp.neg(ay)).arr,
        nonempty,
    )


@jax.jit
def _multi_miller_stage(qx0, qx1, qy0, qy1, px, py):
    """All lanes' Miller loops as ONE batched scan, then the per-lane
    side product.

    Inputs are ``(2, N, L)``: side 0 pairs ``(S_i, G1)``, side 1 pairs
    ``(H_i, -PK_i)``.  Returns the N per-lane ratio products as raw F12
    arrs (leading ``(N,)``), renormed to the stage-boundary bound.
    """

    def rn(a):
        return FV(a, RN_BOUND)

    f = miller_loop(
        F2(rn(qx0), rn(qx1)), F2(rn(qy0), rn(qy1)), rn(px), rn(py)
    )

    def side(i):
        return jax.tree_util.tree_map(
            lambda v: FV(v.arr[i], v.bound),
            f,
            is_leaf=lambda n: isinstance(n, FV),
        )

    return _f12_arrs(_f12_renorm_to(f12_mul(side(0), side(1))))


_MULTIPAIR_KERNELS = (
    ("bls_multipair_aggregate", _multi_g1_neg_aggregate_stage),
    ("bls_multipair_miller", _multi_miller_stage),
    ("bls_finalexp_easy", _easy_part_stage),
    ("bls_finalexp_hard", _hard_part_stage),
    ("bls_finalexp_finish", _finish_stage),
)


def multi_pairing_check(
    sig_x0,
    sig_x1,
    sig_y0,
    sig_y1,
    h_x0,
    h_x1,
    h_y0,
    h_y1,
    pk_x,
    pk_y,
    pk_live,
    lane_live,
):
    """N certificate equations in one batched staged dispatch.

    Inputs: per-lane aggregated seal points ``(N, L)`` x4 components,
    per-lane message points H2(m) ``(N, L)`` x4, per-lane pubkey tables
    ``(N, V, L)`` x2 with their ``(N, V)`` live masks (V a power of two),
    and the ``(N,)`` lane-live mask (padding lanes are False and verify
    False).  Returns an ``(N,)`` bool array — lane i True iff
    ``e(G1, S_i) == e(sum(pk_i), H_i)`` over that lane's live pubkeys.

    Staged exactly like :func:`aggregate_verify_commit` (same pipeline
    rationale), with the final-exponentiation stages SHARED — the jit
    objects are identical, so a process that verified one certificate has
    already compiled most of the batched program.
    """
    with cost_ledger.compile_watch(
        _MULTIPAIR_KERNELS, site="ops/bls12_381.py:multi_pairing_check"
    ):
        npk_x, npk_y, pk_nonempty = _multi_g1_neg_aggregate_stage(
            jnp.asarray(pk_x), jnp.asarray(pk_y), jnp.asarray(pk_live)
        )
        n = npk_x.shape[0]
        gen_x = jnp.broadcast_to(jnp.asarray(_G1_GEN_X), (n,) + _G1_GEN_X.shape)
        gen_y = jnp.broadcast_to(jnp.asarray(_G1_GEN_Y), (n,) + _G1_GEN_Y.shape)
        prod = _multi_miller_stage(
            jnp.stack([jnp.asarray(sig_x0), jnp.asarray(h_x0)]),
            jnp.stack([jnp.asarray(sig_x1), jnp.asarray(h_x1)]),
            jnp.stack([jnp.asarray(sig_y0), jnp.asarray(h_y0)]),
            jnp.stack([jnp.asarray(sig_y1), jnp.asarray(h_y1)]),
            jnp.stack([gen_x, npk_x]),
            jnp.stack([gen_y, npk_y]),
        )
        f = _easy_part_stage(prod)
        t2, t = _hard_part_stage(f)
        return _finish_stage(t2, t, f, pk_nonempty & jnp.asarray(lane_live))
