"""Fused batch-verify + voting-power quorum certification.

The flagship device op of the framework (SURVEY.md §2 #2/#3): one compiled
program takes a round's packed messages and answers both questions the
engine cares about —

1. which messages are valid (signature recovers to the claimed sender, and
   the sender is a validator): a boolean mask aligned with the batch;
2. does the valid set reach quorum: the voting-power-weighted
   ``sum >= floor(2*total/3) + 1`` reduction of the reference's
   ValidatorManager (core/validator_manager.go:95-135), fused after the
   verification so the answer never leaves the device.

Voting-power arithmetic: the reference uses big.Int.  On device, powers are
split into 16-bit low / 15-bit high int32 halves and summed separately —
exact for per-validator powers < 2**31 and <= 2**16 lanes (carry headroom
analysis in :func:`power_reduce`).  Embedders with larger powers use the
host ValidatorManager path, which keeps exact Python ints.

Each validator counts at most once even if the batch (maliciously) carries
several messages from one sender — the reduction is over the *validator*
axis, not the message axis, so Byzantine duplicate-spam cannot inflate
power (the device analogue of the store's one-message-per-sender dedup,
reference messages/messages.go:54-65).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from . import keccak as dk
from . import secp256k1 as sec

__all__ = [
    "digest_words",
    "sig_checks_zw",
    "sender_sig_checks",
    "seal_sig_checks",
    "membership_eq",
    "sender_validity",
    "seal_validity",
    "power_reduce",
    "quorum_certify",
    "seal_quorum_certify",
    "round_certify",
    "split_power",
]


def split_power(power: int) -> Tuple[int, int]:
    """Host-side: split a voting power < 2**31 into (lo16, hi15) int32s."""
    if not 0 <= power < (1 << 31):
        raise ValueError("device quorum path requires powers < 2**31")
    return power & 0xFFFF, power >> 16


def _recover_address(z_limbs, r, s, v):
    qx, qy, ok = sec.ecdsa_recover(z_limbs, r, s, v)
    return dk.pubkey_to_address_words(qx, qy), ok


def digest_words(blocks, nblocks):
    """Batched payload digests as little-endian value words ``(B, 8)``.

    Deliberately a SEPARATE program from the recovery ladder: its compiled
    shape depends on the keccak block bucket (payload sizes vary per phase
    — a round-N PREPREPARE carries a whole RCC), while the expensive ladder
    depends only on the lane bucket.  Splitting means a new payload bucket
    recompiles ~2s of keccak, not ~2min of EC scan.
    """
    digest = dk.keccak256_blocks(blocks, nblocks)  # (B, 8) stream words
    # digest stream words are big-endian value bytes -> little-endian words
    return dk.bswap32(digest[..., ::-1])


def sig_checks_zw(zw, r, s, v, claimed_w, live):
    """Signature checks against pre-computed digest words.

    Recovery succeeds AND the recovered address equals the claimed 20-byte
    address AND the lane is live.  Serves BOTH envelope senders (zw =
    payload digests) and committed seals (zw = the proposal hash) — one
    compiled program per lane bucket."""
    z = dk.words_le_to_limbs(zw, sec.FIELD.nlimbs)
    addr, ok = _recover_address(z, r, s, v)
    match = jnp.all(addr == claimed_w, axis=-1)
    return ok & match & live


def sender_sig_checks(blocks, nblocks, r, s, v, sender_w, live):
    """Envelope checks from raw blocks (digest + recovery fused; used by the
    single-dispatch benchmark/entry path)."""
    return sig_checks_zw(digest_words(blocks, nblocks), r, s, v, sender_w, live)


def seal_sig_checks(hash_zw, r, s, v, signer_w, live):
    """Committed-seal checks: the signed digest is the proposal hash."""
    return sig_checks_zw(hash_zw, r, s, v, signer_w, live)


def membership_eq(sender_w, table_w):
    """``(B, V)`` sender-to-validator-row equality matrix."""
    return jnp.all(sender_w[:, None, :] == table_w[None, :, :], axis=-1)


def sender_validity(blocks, nblocks, r, s, v, sender_w, table_w, live):
    """Envelope validity over a packed batch.

    Returns ``(ok, eq)``: ``ok`` the per-lane validity mask and ``eq`` the
    ``(B, V)`` sender-to-validator equality matrix (reused by the fused
    quorum reduction).
    """
    sig_ok = sender_sig_checks(blocks, nblocks, r, s, v, sender_w, live)
    eq = membership_eq(sender_w, table_w)
    return sig_ok & jnp.any(eq, axis=-1), eq


def seal_validity(hash_zw, r, s, v, signer_w, table_w, live):
    """Committed-seal validity mask + equality matrix."""
    sig_ok = seal_sig_checks(hash_zw, r, s, v, signer_w, live)
    eq = membership_eq(signer_w, table_w)
    return sig_ok & jnp.any(eq, axis=-1), eq


def power_reduce(ok, eq, powers_lo, powers_hi, thr_lo, thr_hi):
    """Exact fused quorum reduction.

    ``ok``: (B,) validity mask; ``eq``: (B, V) sender equality; powers as
    (V,) int32 split halves; threshold as int32 split halves (hi may exceed
    15 bits — it is a sum bound, not a single power).

    Overflow headroom (int32 accumulators): lo-halves < 2**16 and hi-halves
    < 2**15 summed over V <= 2**14 validators stay < 2**30; the lo sum's
    carry is folded into the hi sum before comparing.  Returns
    ``(reached, got_lo, got_hi)`` with ``got = got_hi*2**16 + got_lo`` the
    exact valid voting power (got_lo < 2**16).
    """
    counted = jnp.any(eq & ok[:, None], axis=0)  # (V,) validator counted once
    lo = jnp.sum(jnp.where(counted, powers_lo, 0))
    hi = jnp.sum(jnp.where(counted, powers_hi, 0))
    carry = lo >> 16
    lo = lo & 0xFFFF
    hi = hi + carry
    reached = (hi > thr_hi) | ((hi == thr_hi) & (lo >= thr_lo))
    return reached, lo, hi


@jax.jit
def quorum_certify(
    blocks, nblocks, r, s, v, sender_w, table_w, live, powers_lo, powers_hi, thr_lo, thr_hi
):
    """One fused program: verify a message batch AND certify quorum.

    Returns ``(mask, reached, power_lo, power_hi)``.  This is the
    end-to-end "PREPARE/COMMIT phase check" the engine runs per signal —
    the reference's GetValidMessages + HasQuorum pair
    (core/ibft.go:855-889) collapsed into one device call.
    """
    ok, eq = sender_validity(blocks, nblocks, r, s, v, sender_w, table_w, live)
    reached, lo, hi = power_reduce(ok, eq, powers_lo, powers_hi, thr_lo, thr_hi)
    return ok, reached, lo, hi


@jax.jit
def seal_quorum_certify(
    hash_zw, r, s, v, signer_w, table_w, live, powers_lo, powers_hi, thr_lo, thr_hi
):
    """Fused COMMIT-phase check: seal batch validity + quorum reduction."""
    ok, eq = seal_validity(hash_zw, r, s, v, signer_w, table_w, live)
    reached, lo, hi = power_reduce(ok, eq, powers_lo, powers_hi, thr_lo, thr_hi)
    return ok, reached, lo, hi


@jax.jit
def round_certify(
    blocks,
    nblocks,
    pr,
    ps,
    pv,
    sender_w,
    plive,
    hash_zw,
    sr,
    ss,
    sv,
    signer_w,
    slive,
    table_w,
    powers_lo,
    powers_hi,
    thr_lo,
    thr_hi,
):
    """BOTH phases of a round in ONE device program.

    PREPARE envelopes and COMMIT seals share the identical recovery ladder,
    so their lanes are concatenated and verified in a single batch — one
    kernel launch where :func:`quorum_certify` + :func:`seal_quorum_certify`
    cost two (dispatch latency is material against a <2ms p50 target, and
    one 2B-lane batch vectorizes better than two serialized B-lane ones).
    This is the whole-round certification shape: validating a prepared
    certificate plus committed seals (reference core/ibft.go:1161-1231 +
    messages/helpers.go AreValidPCMessages) or a full round snapshot.

    Returns ``(prep_mask, prep_reached, seal_mask, seal_reached)``.
    """
    zw1 = digest_words(blocks, nblocks)
    zw = jnp.concatenate([zw1, hash_zw], axis=0)
    r = jnp.concatenate([pr, sr], axis=0)
    s = jnp.concatenate([ps, ss], axis=0)
    v = jnp.concatenate([pv, sv], axis=0)
    claimed = jnp.concatenate([sender_w, signer_w], axis=0)
    live = jnp.concatenate([plive, slive], axis=0)
    sig_ok = sig_checks_zw(zw, r, s, v, claimed, live)
    eq = membership_eq(claimed, table_w)
    ok = sig_ok & jnp.any(eq, axis=-1)
    b = zw1.shape[0]
    prep_ok, seal_ok = ok[:b], ok[b:]
    prep_reached, _, _ = power_reduce(
        prep_ok, eq[:b], powers_lo, powers_hi, thr_lo, thr_hi
    )
    seal_reached, _, _ = power_reduce(
        seal_ok, eq[b:], powers_lo, powers_hi, thr_lo, thr_hi
    )
    return prep_ok, prep_reached, seal_ok, seal_reached
