"""Pallas TPU kernel for batched Keccak-f[1600].

The XLA path (:mod:`.keccak`) keeps the state as ``(B, 25, 2)`` uint32 —
fine for fusion, but the trailing axis of 2 is hostile to the VPU's
``(8, 128)`` register tiling: XLA must re-tile every round.  This kernel
flips the layout to **``(50, B)``**: each of the 50 uint32 half-lanes is a
row, and the *batch* rides the 128-wide lane axis — every theta/rho/pi/chi
step is then a plain full-width vector op on ``(B,)`` rows, the layout the
VPU actually wants.  One grid step processes a 128-message tile held in
VMEM for all 24 rounds (zero HBM traffic between rounds).

The 24 rounds run under a ``lax.fori_loop`` INSIDE the kernel with the
round constants streamed from a small input ref — one round's straight-line
body is traced once (the fully unrolled graph is pathological to compile on
XLA:CPU in interpret mode, the same reason ``keccak.keccak_f`` scans), and
the whole loop runs register/VMEM-resident with no per-round HBM traffic.

Wired into the digest path through :func:`go_ibft_tpu.ops.keccak.keccak_f`
when ``GO_IBFT_PALLAS=1`` (TPU backends; ``GO_IBFT_PALLAS=interpret``
forces the interpreter on any backend for tests/debugging).  Reference
scope: this accelerates the digest half of the embedder's ``Verifier``
seam (go-ibft core/backend.go:37-56); the state-machine semantics above
it are unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .keccak import _RC, _ROT

__all__ = ["keccak_f_pallas", "pallas_supported"]

_TILE = 128  # batch tile per grid step: the VPU lane width


def pallas_supported() -> bool:
    """True when the active backend can run this kernel compiled (TPU).

    "axon" is the tunneled TPU PJRT plugin — same Mosaic compile path.
    This is THE predicate for compiled-vs-interpret dispatch; keccak's
    ``_pallas_mode`` and the A/B + warm scripts all route through it so
    they can never disagree about which variant actually runs.
    """
    return jax.default_backend() in ("tpu", "axon")


def _rotl_halves(lo, hi, n: int):
    """64-bit rotate-left by a STATIC amount on (lo, hi) uint32 rows."""
    n %= 64
    if n == 0:
        return lo, hi
    if n >= 32:
        lo, hi = hi, lo
        n -= 32
        if n == 0:
            return lo, hi
    return (
        (lo << n) | (hi >> (32 - n)),
        (hi << n) | (lo >> (32 - n)),
    )


def _keccak_f_kernel(rc_ref, st_ref, out_ref):
    """24 Keccak rounds (``fori_loop``) on a ``(50, B)`` uint32 VMEM block.

    Row ``2*i`` is the low half of lane ``i``, row ``2*i + 1`` the high
    half; lanes are indexed ``x + 5*y`` per the Keccak spec.  ``rc_ref``
    holds the 24 round constants as ``(24, 2)`` uint32 (lo, hi).
    """

    def round_body(r, st):
        a = [(st[2 * i], st[2 * i + 1]) for i in range(25)]
        # theta: column parities and the d-mix
        c = []
        for x in range(5):
            lo = a[x][0] ^ a[x + 5][0] ^ a[x + 10][0] ^ a[x + 15][0] ^ a[x + 20][0]
            hi = a[x][1] ^ a[x + 5][1] ^ a[x + 10][1] ^ a[x + 15][1] ^ a[x + 20][1]
            c.append((lo, hi))
        d = []
        for x in range(5):
            rlo, rhi = _rotl_halves(*c[(x + 1) % 5], 1)
            d.append((c[(x - 1) % 5][0] ^ rlo, c[(x - 1) % 5][1] ^ rhi))
        a = [
            (a[x + 5 * y][0] ^ d[x][0], a[x + 5 * y][1] ^ d[x][1])
            for y in range(5)
            for x in range(5)
        ]
        # rho + pi: B[y, 2x+3y] = rotl(A[x, y], r[x][y])
        b = [None] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl_halves(
                    *a[x + 5 * y], _ROT[x][y]
                )
        # chi
        a = [
            (
                b[x + 5 * y][0]
                ^ (~b[(x + 1) % 5 + 5 * y][0] & b[(x + 2) % 5 + 5 * y][0]),
                b[x + 5 * y][1]
                ^ (~b[(x + 1) % 5 + 5 * y][1] & b[(x + 2) % 5 + 5 * y][1]),
            )
            for y in range(5)
            for x in range(5)
        ]
        # iota
        a[0] = (a[0][0] ^ rc_ref[r, 0], a[0][1] ^ rc_ref[r, 1])
        return jnp.stack([half for lane in a for half in lane], axis=0)

    out_ref[:] = jax.lax.fori_loop(0, 24, round_body, st_ref[:])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _keccak_f_rows(st: jnp.ndarray, *, interpret: bool) -> jnp.ndarray:
    """The compiled unit: ``(50, k*TILE)`` rows in, same shape out.

    Kept separate from the padding/layout wrapper so every batch size B
    that rounds up to the same tile count shares ONE compile (the unrolled
    24-round graph is expensive to build on XLA:CPU — don't retrace it per
    caller shape)."""
    from jax.experimental import pallas as pl

    rc = jnp.asarray(
        np.asarray([[c & 0xFFFFFFFF, c >> 32] for c in _RC], dtype=np.uint32)
    )
    return pl.pallas_call(
        _keccak_f_kernel,
        out_shape=jax.ShapeDtypeStruct(st.shape, jnp.uint32),
        grid=(st.shape[1] // _TILE,),
        in_specs=[
            pl.BlockSpec((24, 2), lambda i: (0, 0)),  # round constants
            pl.BlockSpec((50, _TILE), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((50, _TILE), lambda i: (0, i)),
        interpret=interpret,
    )(rc, st)


def keccak_f_pallas(state: jnp.ndarray, *, interpret: bool = False) -> jnp.ndarray:
    """Keccak-f[1600] on a ``(B, 25, 2)`` uint32 state via the Pallas kernel.

    Drop-in for :func:`go_ibft_tpu.ops.keccak.keccak_f` on 1-D batches.
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU
    correctness tests); compiled mode requires a TPU backend.  The
    layout/padding transform runs eagerly; only the fixed-shape row kernel
    is jitted.
    """
    if state.ndim != 3 or state.shape[-2:] != (25, 2):
        raise ValueError(f"expected (B, 25, 2) state, got {state.shape}")
    b = state.shape[0]
    bpad = -(-b // _TILE) * _TILE
    # (B, 25, 2) -> (50, Bpad): half-lanes become rows, batch rides lanes.
    st = jnp.transpose(jnp.asarray(state).reshape(b, 50))
    st = jnp.pad(st, ((0, 0), (0, bpad - b)))
    out = _keccak_f_rows(st, interpret=interpret)
    return jnp.transpose(out)[:b].reshape(b, 25, 2)


def keccak_f_reference(state: np.ndarray) -> np.ndarray:
    """Pure-numpy uint64 oracle for the kernel tests."""
    lanes = (
        state[..., 0].astype(np.uint64) | (state[..., 1].astype(np.uint64) << 32)
    )  # (B, 25)
    out = np.empty_like(lanes)
    for row in range(lanes.shape[0]):
        a = list(lanes[row])
        for rc in _RC:
            c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
            d = [
                c[(x - 1) % 5]
                ^ ((c[(x + 1) % 5] << np.uint64(1)) | (c[(x + 1) % 5] >> np.uint64(63)))
                for x in range(5)
            ]
            a = [a[x + 5 * y] ^ d[x] for y in range(5) for x in range(5)]
            b = [np.uint64(0)] * 25
            for x in range(5):
                for y in range(5):
                    r = _ROT[x][y]
                    v = a[x + 5 * y]
                    b[y + 5 * ((2 * x + 3 * y) % 5)] = (
                        ((v << np.uint64(r)) | (v >> np.uint64(64 - r)))
                        if r
                        else v
                    )
            a = [
                b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y])
                for y in range(5)
                for x in range(5)
            ]
            a[0] ^= np.uint64(rc)
        out[row] = a
    res = np.empty(state.shape, dtype=np.uint32)
    res[..., 0] = out & np.uint64(0xFFFFFFFF)
    res[..., 1] = out >> np.uint64(32)
    return res
