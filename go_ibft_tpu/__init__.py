"""go_ibft_tpu: a TPU-native IBFT 2.0 consensus framework.

A from-scratch re-design of the capability set of 0xPolygon/go-ibft
(reference mounted at /root/reference) for TPU hardware:

- Host side: an asyncio consensus engine (``go_ibft_tpu.core``) driving the
  IBFT 2.0 state machine — branchy, latency-bound control flow stays off the
  accelerator, mirroring the reference's split between the state machine
  (reference core/ibft.go) and expensive predicates (core/backend.go Verifier).
- Device side: the O(N)-per-phase data plane — Keccak-256 hashing, ECDSA
  secp256k1 / BLS12-381 signature verification and voting-power quorum
  reduction — runs as jit/vmap-batched JAX ops (``go_ibft_tpu.ops``) draining a
  whole round's message store in one fixed-shape batch instead of the
  reference's per-message sequential verifies.
- Scale: ``go_ibft_tpu.parallel`` shards verification batches over a
  ``jax.sharding.Mesh`` and provides a lock-step multi-validator cluster
  simulation where "multicast" is an all_gather over ICI.
- Chain: ``go_ibft_tpu.chain`` turns the per-height engine into a
  continuously running validator node — a persistent multi-height sequencer
  with no inter-height barrier, WAL crash recovery, and batched block-sync
  catch-up (docs/CHAIN.md).
"""

__version__ = "0.1.0"
