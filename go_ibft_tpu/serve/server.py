"""ProofServer / ProofVerifier: the cache-and-coalesce read plane.

The write side of this node (PRs 5-9) finalizes fast; this module makes
that pay off for users who are NOT validators.  Thousands of concurrent
untrusted-client proof requests reduce to a small number of shared
device/host drains through three mechanisms:

* **Canonical-range proof cache** (``serve/cache.py``): finality is
  irreversible, so full chunks are built once, self-checked once, and
  served forever; overlapping client ranges share chunk entries, and a
  per-chunk build lock coalesces the cold stampede (1000 clients asking
  for the same cold chunk build it exactly once).
* **Shared signature-verdict cache**: a committed seal's validity —
  ``recover(proposal_hash, sig) == signer`` — mentions no validator set
  and no client, so the verdict for a ``(hash, signer, signature)`` lane
  is process-shareable.  N clients verifying overlapping ranges pay the
  recover ONCE; membership and quorum (the per-client part) are exact
  host dict arithmetic over each client's own diff-walked set.  The same
  split that makes the multi-tenant dispatcher exact
  (``sched/dispatch.py``) makes this cache sound.
* **Coalesced fresh drains**: cache-miss lanes submit through a
  read-tier :class:`~go_ibft_tpu.sched.TenantScheduler` handle (when one
  is attached), so concurrent ``verify_proof`` calls — and the server's
  own pre-serve self-check — merge into shared batched dispatches
  instead of per-client sequential verifies.  The read tier is
  priority-classed below consensus: a proof flood can never starve a
  live round (the QoS satellite, pinned in tests/test_serve.py).

Verdict honesty: every accept/reject is pinned to the sequential oracle
— signature validity comes from the same verifier ladder every other
drain uses (an any-signer membership source reduces its mask to exactly
signature validity), and membership/quorum are exact Python ints per
client.  The conformance tests pin proof verdicts lane-for-lane against
:class:`~go_ibft_tpu.verify.batch.HostBatchVerifier`.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

from ..core.validator_manager import calculate_quorum
from ..crypto.backend import proposal_hash_of
from ..obs import ledger as cost_ledger
from ..obs import trace
from ..utils import metrics
from ..verify.batch import HostBatchVerifier
from .cache import CachedChunk, ProofCache
from .proof import (
    FinalityProof,
    ProofBuilder,
    ProofEntry,
    ProofError,
    SetDiff,
    walk_sets,
)

__all__ = [
    "ProofServer",
    "ProofVerifier",
    "SigVerdictCache",
    "any_signer_source",
    "SERVE_PROOFS_KEY",
    "SERVE_VERIFY_LANES_KEY",
    "SERVE_SIG_HITS_KEY",
    "SERVE_PAIRINGS_KEY",
]

SERVE_PROOFS_KEY = ("go-ibft", "serve", "proofs_served")
SERVE_VERIFY_LANES_KEY = ("go-ibft", "serve", "verify_lanes")
SERVE_SIG_HITS_KEY = ("go-ibft", "serve", "sig_cache_hits")
SERVE_PAIRINGS_KEY = ("go-ibft", "serve", "pairings")
# Fixed-bucket proof-serving latency for the /metrics endpoint (off
# unless metrics.enable_fixed_histograms() ran).
SERVE_PROOF_MS_KEY = ("go-ibft", "latency", "serve_proof_ms")

_VERIFIER_IDS = itertools.count()


class _AnySigner(Mapping):
    """Membership-vacuous validator source: every address is a member.

    Feeding this to a verifier (or a scheduler tenant) reduces its
    ``signature-valid AND member`` mask to pure signature validity — the
    chain-agnostic half of the predicate, exactly the trick the
    multi-tenant dispatcher uses with its claimed-address table.  The
    per-client membership AND happens afterwards against the client's own
    diff-walked set."""

    def __contains__(self, _addr) -> bool:
        return True

    def __getitem__(self, _addr) -> int:
        return 1

    def __iter__(self):
        return iter(())

    def __len__(self) -> int:
        return 1  # never falsy: emptiness checks must not reject the source


_ANY_SIGNERS = _AnySigner()


def any_signer_source(_height: int) -> Mapping[bytes, int]:
    """``validators_for_height`` seam returning the any-signer set."""
    return _ANY_SIGNERS


class SigVerdictCache:
    """Process-wide ``(proposal_hash, signer, signature) -> sig valid``.

    Sound to share across clients and heights because the key pins every
    input of the recover: the verdict is a pure function of the lane
    bytes.  Bounded LRU (a verdict is one bool; the default cap holds
    ~256k lanes), thread-safe, hit/miss counters for the evidence line.
    """

    def __init__(self, cap: int = 262_144):
        if cap < 1:
            raise ValueError("sig-verdict cache cap must be >= 1")
        self.cap = cap
        self._lock = threading.Lock()
        self._verdicts: "OrderedDict[tuple, bool]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(proposal_hash: bytes, seal) -> tuple:
        return (proposal_hash, seal.signer, seal.signature)

    def lookup_batch(
        self, keys: List[tuple]
    ) -> Tuple[Dict[int, bool], List[int]]:
        """Split ``keys`` into cached verdicts (index -> verdict) and the
        indices that must verify fresh."""
        known: Dict[int, bool] = {}
        fresh: List[int] = []
        with self._lock:
            for i, key in enumerate(keys):
                verdict = self._verdicts.get(key)
                if verdict is None:
                    self.misses += 1
                    fresh.append(i)
                else:
                    self._verdicts.move_to_end(key)
                    self.hits += 1
                    known[i] = verdict
        if known:
            metrics.inc_counter(SERVE_SIG_HITS_KEY, len(known))
        return known, fresh

    def store_batch(self, keys: List[tuple], verdicts) -> None:
        with self._lock:
            for key, verdict in zip(keys, verdicts):
                self._verdicts[key] = bool(verdict)
                self._verdicts.move_to_end(key)
            while len(self._verdicts) > self.cap:
                self._verdicts.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._verdicts.clear()

    def stats(self) -> dict:
        with self._lock:
            hits, misses, size = self.hits, self.misses, len(self._verdicts)
        lookups = hits + misses
        return {
            "entries": size,
            "cap": self.cap,
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / lookups, 3) if lookups else None,
        }


class ProofVerifier:
    """Client-side (and pre-serve self-check) finality-proof verification.

    Checks, in cost order, for a proof against a trusted ``(checkpoint
    height, powers)`` anchor:

    1. structure + diff-chain walk (:func:`~go_ibft_tpu.serve.proof.
       walk_sets`) — contiguity, ascending in-range diffs, no diff on the
       anchor height;
    2. evidence-form exclusivity — an entry carrying BOTH a certificate
       and a seal list is rejected (the sync client's smuggling gate,
       enforced at the serve layer too);
    3. certificate entries: hash-binding to the served header for EVERY
       entry first, then ALL surviving certificates through ONE batched
       multi-pairing dispatch (:meth:`~go_ibft_tpu.crypto.quorum_cert.
       BLSCertifier.verify_many`, ISSUE 12) built over the diff-walked
       sets (so a certificate spliced across a rotation verifies against
       the RIGHT set — or fails).  Requires ``bls_keys_for_height`` (a
       PoP-gated registry); a cert-carrying proof without one is a
       :class:`ProofError`, never silently trusted;
    4. seal entries: one batched signature-validity drain for every lane
       not already in the shared :class:`SigVerdictCache` (through the
       scheduler read tier when attached — concurrent callers coalesce),
       then per-height membership + voting-power quorum over the walked
       set (exact ints, per client).

    ``scheduler`` routes fresh drains through a read-tier tenant;
    ``lane_verifier`` overrides the drain engine (any object with
    ``verify_seal_lanes``; it MUST be built over
    :func:`any_signer_source` so its mask is pure signature validity).
    """

    def __init__(
        self,
        *,
        scheduler=None,
        lane_verifier=None,
        bls_keys_for_height: Optional[Callable[[int], Mapping]] = None,
        sig_cache: Optional[SigVerdictCache] = None,
        tenant_id: Optional[str] = None,
        require_commitments: bool = False,
    ):
        # ISSUE 20: enforce next-set content commitments per diff hop
        # (lightsync/commitment.py).  Off by default — proofs from
        # commitment-free chains predate the scheme and must keep
        # verifying; a client of a commitment-producing chain turns it
        # on and fabricated/omitted rotations die in walk_sets.
        self._require_commitments = require_commitments
        self._sched = None
        self._tenant_id = None
        if lane_verifier is not None:
            self._verifier = lane_verifier
        elif scheduler is not None:
            self._sched = scheduler
            self._tenant_id = tenant_id or f"serve-verify-{next(_VERIFIER_IDS)}"
            self._verifier = scheduler.register(
                self._tenant_id, any_signer_source, priority="read"
            )
        else:
            self._verifier = HostBatchVerifier(any_signer_source)
        self._bls_keys = bls_keys_for_height
        self.sig_cache = sig_cache if sig_cache is not None else SigVerdictCache()
        # Counter lock: verify() is documented thread-safe, and LOAD/ADD/
        # STORE increments from concurrent clients would lose updates.
        self._stats_lock = threading.Lock()
        self.proofs_verified = 0
        self.lanes_verified = 0
        self.pairings = 0

    def close(self) -> None:
        """Release the scheduler tenant (no-op without a scheduler)."""
        if self._sched is not None and self._tenant_id is not None:
            self._sched.unregister(self._tenant_id)
            self._tenant_id = None

    # -- verification ----------------------------------------------------

    def verify(
        self,
        proof: FinalityProof,
        trusted_powers: Mapping[bytes, int],
    ) -> dict:
        """Verify ``proof`` against the trusted checkpoint powers.

        Returns a report dict (heights/lanes/cache-hit evidence) on
        acceptance; raises :class:`ProofError` naming the failing height
        on rejection.  Thread-safe — concurrent calls share the sig-
        verdict cache and (with a scheduler) coalesce their fresh drains.
        """
        sets = walk_sets(
            trusted_powers,
            proof,
            require_commitments=self._require_commitments,
        )
        lanes: List[Tuple[bytes, object]] = []
        cert_entries: List[ProofEntry] = []
        for entry in proof.entries:
            if entry.cert is not None and entry.seals:
                raise ProofError(
                    f"height {entry.height}: certificate entry carries a "
                    "seal list (unverifiable evidence mix)"
                )
            if entry.cert is not None:
                cert_entries.append(entry)
            else:
                proposal_hash = proposal_hash_of(entry.proposal)
                lanes.extend((proposal_hash, seal) for seal in entry.seals)
        with trace.span(
            "serve.verify",
            heights=len(proof.entries),
            lanes=len(lanes),
            certs=len(cert_entries),
        ):
            sig_ok = self._sig_validity(lanes)
            pairings = self._verify_certs(cert_entries, sets)
            self._check_quorums(proof, sets, sig_ok)
        with self._stats_lock:
            self.proofs_verified += 1
            self.lanes_verified += len(lanes)
            self.pairings += pairings
        metrics.inc_counter(SERVE_VERIFY_LANES_KEY, len(lanes))
        return {
            "checkpoint": proof.checkpoint_height,
            "target": proof.target,
            "heights": len(proof.entries),
            "lanes": len(lanes),
            "pairings": pairings,
        }

    def _sig_validity(self, lanes: List[tuple]) -> np.ndarray:
        """Shared-cache + coalesced-drain signature validity per lane."""
        sig_ok = np.zeros(len(lanes), dtype=bool)
        if not lanes:
            return sig_ok
        keys = [
            SigVerdictCache.key(proposal_hash, seal)
            for proposal_hash, seal in lanes
        ]
        known, fresh = self.sig_cache.lookup_batch(keys)
        for i, verdict in known.items():
            sig_ok[i] = verdict
        if fresh:
            # One drain for every fresh lane of the whole proof.  The
            # membership source is any-signer, so the height argument
            # only labels the drain — every lane carries its OWN
            # proposal hash (the verify_seal_lanes shape).  route_tag:
            # a DIRECT drain records in the cost ledger as
            # ``serve/<route>``.  With a TenantScheduler attached the
            # tag intentionally does NOT propagate: the scheduler's
            # flush thread coalesces lanes from many tenants into ONE
            # dispatch, so per-consumer attribution of that launch is
            # undefined by construction — scheduled serve work shows up
            # under the scheduler's own route like every other tenant's.
            with cost_ledger.route_tag("serve"):
                mask = np.asarray(
                    self._verifier.verify_seal_lanes(
                        [lanes[i] for i in fresh], 0
                    ),
                    dtype=bool,
                )
            for j, i in enumerate(fresh):
                sig_ok[i] = mask[j]
            self.sig_cache.store_batch([keys[i] for i in fresh], mask)
        return sig_ok

    def _verify_certs(self, cert_entries: List[ProofEntry], sets) -> int:
        if not cert_entries:
            return 0
        if self._bls_keys is None:
            raise ProofError(
                "proof carries aggregate quorum certificates but this "
                "verifier has no BLS key source to check them"
            )
        from ..crypto.quorum_cert import BLSCertifier

        # The certifier's power source is the DIFF-WALKED set, not any
        # server-trusted snapshot: a certificate spliced across a
        # rotation verifies against the set the client derived for that
        # height, or fails (the rotation-aware satellite).
        certifier = BLSCertifier(lambda h: sets[h], self._bls_keys)
        # Hash-binding gates for EVERY entry run before any pairing work
        # (a relabeled certificate must cost zero crypto — pinned in
        # tests/test_serve.py); the survivors then verify as ONE batched
        # multi-pairing dispatch (ISSUE 12: a multi-height cert proof is
        # one dispatch, not one pairing call per height).
        for entry in cert_entries:
            cert = entry.cert
            if (
                cert.height != entry.height
                or cert.proposal_hash != proposal_hash_of(entry.proposal)
            ):
                raise ProofError(
                    f"height {entry.height}: certificate does not bind "
                    "the served header"
                )
        with trace.span(
            "serve.cert_verify", heights=len(cert_entries)
        ):
            # Ledger attribution: the batched multi-pairing this issues
            # records as ``serve/<route>`` (see _sig_validity).
            with cost_ledger.route_tag("serve"):
                mask = np.asarray(
                    certifier.verify_many([e.cert for e in cert_entries]),
                    dtype=bool,
                )
        for entry, ok in zip(cert_entries, mask):
            if not bool(ok):
                raise ProofError(
                    f"height {entry.height}: aggregate quorum certificate "
                    "failed verification"
                )
        pairings = len(cert_entries)
        metrics.inc_counter(SERVE_PAIRINGS_KEY, pairings)
        return pairings

    @staticmethod
    def _check_quorums(
        proof: FinalityProof, sets, sig_ok: np.ndarray
    ) -> None:
        offset = 0
        for entry in proof.entries:
            if entry.cert is not None:
                continue
            mask = sig_ok[offset : offset + len(entry.seals)]
            offset += len(entry.seals)
            powers = sets[entry.height]
            # Distinct signers only (a duplicated seal must not double its
            # power), membership against the walked set.
            signers = {
                seal.signer
                for seal, ok in zip(entry.seals, mask)
                if bool(ok) and seal.signer in powers
            }
            quorum = calculate_quorum(sum(powers.values()))
            got = sum(powers[a] for a in signers)
            if got < quorum:
                raise ProofError(
                    f"height {entry.height}: committed-seal power {got} < "
                    f"quorum {quorum} "
                    f"({int(mask.sum())}/{len(entry.seals)} seals valid)"
                )

    def stats(self) -> dict:
        with self._stats_lock:
            proofs, lanes = self.proofs_verified, self.lanes_verified
            pairings = self.pairings
        return {
            "proofs_verified": proofs,
            "lanes_verified": lanes,
            "pairings": pairings,
            "sig_cache": self.sig_cache.stats(),
        }


class ProofServer:
    """Serves finality proofs over a :class:`~go_ibft_tpu.serve.proof.
    ProofBuilder`, with canonical-chunk caching, stampede coalescing, and
    a pre-serve self-check.

    ``self_check=True`` (default) verifies every freshly-built chunk (and
    every partial tail segment) through the SAME verifier clients use
    before it is served or cached — a corrupted local chain, a WAL
    decode bug, or a builder regression surfaces here, not at a client.
    Self-check drains warm the shared sig-verdict cache, so the first
    client verifying a just-served range pays near zero crypto.
    """

    def __init__(
        self,
        builder: ProofBuilder,
        cache: Optional[ProofCache] = None,
        *,
        scheduler=None,
        lane_verifier=None,
        bls_keys_for_height: Optional[Callable[[int], Mapping]] = None,
        sig_cache: Optional[SigVerdictCache] = None,
        self_check: bool = True,
        max_proof_heights: int = 4096,
        tenant_id: Optional[str] = None,
    ) -> None:
        self.builder = builder
        self.cache = cache if cache is not None else ProofCache()
        # ``sig_cache`` makes the verdict cache genuinely process-wide:
        # multiple servers (or a server plus standalone verifiers) over
        # the same chain share one by passing it explicitly.
        self.verifier = ProofVerifier(
            scheduler=scheduler,
            lane_verifier=lane_verifier,
            bls_keys_for_height=bls_keys_for_height,
            sig_cache=sig_cache,
            tenant_id=tenant_id,
        )
        self.self_check = self_check
        self.max_proof_heights = max_proof_heights
        self._locks_guard = threading.Lock()
        self._build_locks: Dict[int, threading.Lock] = {}
        # Concurrent clients increment these; bare += would lose updates.
        self._stats_lock = threading.Lock()
        self.proofs_served = 0
        self.chunks_built = 0

    def close(self) -> None:
        self.verifier.close()

    # -- serving ---------------------------------------------------------

    def get_proof(
        self, checkpoint_height: int, target: Optional[int] = None
    ) -> FinalityProof:
        """Assemble the proof for ``(checkpoint_height, target]``.

        ``target`` defaults to (and is clamped at) the chain's latest
        finalized height; ranges are also clamped to
        ``max_proof_heights`` (the sync client's bounded-batch posture —
        a cold client loops).  Raises :class:`ProofError` when the range
        is empty or the chain cannot serve it.
        """
        t0 = time.perf_counter() if metrics.fixed_histograms_enabled() else None
        latest = self.builder.latest_height()
        if target is None:
            target = latest
        target = min(target, latest, checkpoint_height + self.max_proof_heights)
        if checkpoint_height < 0 or target <= checkpoint_height:
            raise ProofError(
                f"nothing to prove past checkpoint {checkpoint_height} "
                f"(target {target}, latest finalized {latest})"
            )
        start = checkpoint_height + 1
        entries: List[ProofEntry] = []
        diffs: List[SetDiff] = []
        with trace.span(
            "serve.proof", start=start, target=target
        ):
            for chunk_start in self.cache.chunk_starts(start, target):
                chunk_end = chunk_start + self.cache.chunk_heights - 1
                if chunk_end <= latest:
                    chunk = self._full_chunk(chunk_start)
                else:
                    # Partial tail window: still growing, never cached.
                    chunk = self._tail_segment(chunk_start, target)
                for entry in chunk.entries:
                    if start <= entry.height <= target:
                        entries.append(entry)
                for diff in chunk.diffs:
                    if start < diff.height <= target:
                        diffs.append(diff)
        with self._stats_lock:
            self.proofs_served += 1
        metrics.inc_counter(SERVE_PROOFS_KEY)
        if t0 is not None:
            metrics.observe_fixed(
                SERVE_PROOF_MS_KEY, (time.perf_counter() - t0) * 1e3
            )
        return FinalityProof(
            checkpoint_height=checkpoint_height, entries=entries, diffs=diffs
        )

    def verify_proof(
        self, proof: FinalityProof, trusted_powers: Mapping[bytes, int]
    ) -> dict:
        """Verify a proof through the server's shared read plane (the
        coalescing entry point N client sessions share)."""
        return self.verifier.verify(proof, trusted_powers)

    # -- chunk machinery -------------------------------------------------

    def _full_chunk(self, chunk_start: int) -> CachedChunk:
        chunk = self.cache.get(chunk_start)
        if chunk is not None:
            return chunk
        with self._locks_guard:
            lock = self._build_locks.setdefault(chunk_start, threading.Lock())
        try:
            with lock:
                # Re-check under the build lock: the cold-range stampede
                # coalesces here — whoever lost the race finds the
                # winner's chunk and builds nothing.
                chunk = self.cache.peek(chunk_start)
                if chunk is not None:
                    return chunk
                chunk_end = chunk_start + self.cache.chunk_heights - 1
                with trace.span(
                    "serve.build", start=chunk_start, end=chunk_end
                ):
                    entries, diffs = self.builder.build_range(
                        chunk_start, chunk_end
                    )
                    if self.self_check:
                        self._self_check(chunk_start, entries, diffs)
                chunk = self.cache.put(chunk_start, entries, diffs)
                with self._stats_lock:
                    self.chunks_built += 1
            return chunk
        finally:
            with self._locks_guard:
                self._build_locks.pop(chunk_start, None)

    def _tail_segment(self, seg_start: int, target: int) -> CachedChunk:
        with trace.span("serve.build", start=seg_start, end=target, tail=True):
            entries, diffs = self.builder.build_range(seg_start, target)
            if self.self_check:
                self._self_check(seg_start, entries, diffs)
        return CachedChunk(
            start=seg_start,
            end=target,
            entries=tuple(entries),
            diffs=tuple(diffs),
        )

    def _self_check(
        self,
        seg_start: int,
        entries: List[ProofEntry],
        diffs: List[SetDiff],
    ) -> None:
        """Pre-serve verification of a freshly-built segment, anchored at
        the server's own snapshot for the segment's first height.  Runs
        through the shared read plane, so its drains coalesce with (and
        pre-warm the sig cache for) concurrent client verifies."""
        segment = FinalityProof(
            checkpoint_height=seg_start - 1,
            entries=list(entries),
            diffs=[d for d in diffs if d.height > seg_start],
        )
        try:
            self.verifier.verify(
                segment, self.builder.validators_for_height(seg_start)
            )
        except ProofError as err:
            raise ProofError(
                f"pre-serve self-check failed for heights "
                f"[{seg_start}, {entries[-1].height}]: {err}"
            ) from err

    # -- evidence --------------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            served, built = self.proofs_served, self.chunks_built
        return {
            "proofs_served": served,
            "chunks_built": built,
            "cache": self.cache.stats(),
            "verify": self.verifier.stats(),
        }
