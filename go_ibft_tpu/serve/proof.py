"""Finality proofs: the light-client payload the serve plane assembles.

"Practical Light Clients for Committee-Based Blockchains" (PAPERS.md,
2410.03347) reduces catching a light client up to a committee chain to
three ingredients per height: the header, the commit-quorum evidence
(per-validator seals, or — "Performance of EdDSA and BLS Signatures in
Committee-Based Consensus", 2302.00418 — one O(1) aggregate quorum
certificate), and the validator-set changes connecting the client's
trusted checkpoint to the target height.  This module is those three
ingredients as data:

* :class:`ProofEntry` — one height's header (the consensus ``Proposal``)
  plus exactly ONE evidence form: a seal list or an
  :class:`~go_ibft_tpu.crypto.quorum_cert.AggregateQuorumCertificate`
  (both at once is the evidence-smuggling shape the sync client rejects,
  and proof verification rejects it too — see ``serve/server.py``);
* :class:`SetDiff` — the validator-set rotation taking effect AT a
  height, as ``added`` (address -> power, covering power changes) and
  ``removed`` deltas against the previous height's set;
* :class:`FinalityProof` — a contiguous range of entries anchored at the
  client's trusted ``checkpoint_height``, with the ascending diff chain
  for every rotation inside the range.

:class:`ProofBuilder` assembles these from a
:class:`~go_ibft_tpu.chain.sync.SyncSource` (``ChainRunner`` implements
it) and the per-height validator-set snapshot seam
(``validators_for_height`` — the same callable every verifier takes).
Because IBFT finality is irreversible, a built entry never changes; the
height-range cache (``serve/cache.py``) exploits exactly that.

Trust posture (docs/SERVING.md): the client trusts its checkpoint — a
``(height, validator powers for height+1)`` pair — and everything else
is re-derived: each height's quorum is re-checked against the set
obtained by applying the served diffs hop by hop from the checkpoint, so
a proof spliced across a substantive rotation with the STALE set fails
quorum at the first post-rotation height.  The walk alone catches
omission and staleness; FABRICATION is closed by next-set content
commitments (ISSUE 20, ``go_ibft_tpu/lightsync/commitment.py``): a
producing embedder embeds the NEXT height's set root inside the signed
proposal bytes, and :func:`walk_sets` checks every hop's derived set
against the root the PREVIOUS height's quorum sealed — a server-invented
rotation (or an omitted one) now fails at the commitment check, no
old-quorum signature over the diff required.  Enforcement is opt-in per
verifier (``require_commitments=True``) because commitment-free chains
predate the scheme; when enforced, a hop without a commitment is itself
an error.  The remaining epoch-boundary assumptions are documented in
docs/SERVING.md's trust assumptions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from ..chain.sync import SyncSource
from ..chain.wal import FinalizedBlock
from ..lightsync.commitment import extract_next_set, set_root
from ..messages.helpers import CommittedSeal
from ..messages.wire import Proposal

__all__ = [
    "FinalityProof",
    "ProofBuilder",
    "ProofEntry",
    "ProofError",
    "SetDiff",
    "diff_chain",
    "walk_sets",
]

PROOF_WIRE_VERSION = 1


class ProofError(ValueError):
    """A finality proof failed structural or cryptographic verification
    (or could not be built for the requested range)."""


@dataclass
class SetDiff:
    """Validator-set rotation taking effect AT ``height``.

    ``added`` maps address -> voting power and doubles as the
    power-change form (an address present in both the old set and
    ``added`` takes the new power); ``removed`` lists addresses leaving
    the set.  Applying the diff to the set of ``height - 1`` yields the
    set of ``height``.
    """

    height: int
    added: Dict[bytes, int] = field(default_factory=dict)
    removed: Tuple[bytes, ...] = ()

    def apply(self, powers: Mapping[bytes, int]) -> Dict[bytes, int]:
        out = dict(powers)
        for addr in self.removed:
            out.pop(addr, None)
        out.update(self.added)
        return out

    # -- wire ------------------------------------------------------------

    def to_wire(self) -> dict:
        return {
            "height": self.height,
            "added": {a.hex(): int(p) for a, p in self.added.items()},
            "removed": [a.hex() for a in self.removed],
        }

    @classmethod
    def from_wire(cls, rec: dict) -> "SetDiff":
        return cls(
            height=int(rec["height"]),
            added={
                bytes.fromhex(a): int(p) for a, p in rec.get("added", {}).items()
            },
            removed=tuple(bytes.fromhex(a) for a in rec.get("removed", ())),
        )


@dataclass
class ProofEntry:
    """One finalized height: header + commit-quorum evidence.

    Mirrors :class:`~go_ibft_tpu.chain.wal.FinalizedBlock` (``seals`` and
    ``cert`` are mutually exclusive — the WAL writes them that way and
    verification REJECTS an entry carrying both, the same smuggling gate
    the sync client enforces).
    """

    height: int
    proposal: Proposal
    seals: List[CommittedSeal] = field(default_factory=list)
    cert: Optional[object] = None  # AggregateQuorumCertificate

    @classmethod
    def from_block(cls, block: FinalizedBlock) -> "ProofEntry":
        return cls(
            height=block.height,
            proposal=block.proposal,
            seals=list(block.seals),
            cert=block.cert,
        )

    # -- wire (the WAL's hex-through-the-codec record shape) -------------

    def to_wire(self) -> dict:
        rec = {
            "height": self.height,
            "proposal": self.proposal.encode().hex(),
        }
        if self.cert is not None:
            rec["cert"] = self.cert.encode().hex()
        rec["seals"] = [
            [s.signer.hex(), s.signature.hex()] for s in self.seals
        ]
        return rec

    @classmethod
    def from_wire(cls, rec: dict) -> "ProofEntry":
        cert_hex = rec.get("cert")
        cert = None
        if cert_hex is not None:
            from ..crypto.quorum_cert import AggregateQuorumCertificate

            cert = AggregateQuorumCertificate.decode(bytes.fromhex(cert_hex))
        return cls(
            height=int(rec["height"]),
            proposal=Proposal.decode(bytes.fromhex(rec["proposal"])),
            seals=[
                CommittedSeal(
                    signer=bytes.fromhex(signer),
                    signature=bytes.fromhex(signature),
                )
                for signer, signature in rec.get("seals", ())
            ],
            cert=cert,
        )


@dataclass
class FinalityProof:
    """A contiguous finality-proof range anchored at a trusted checkpoint.

    ``entries`` cover heights ``checkpoint_height + 1 .. target``
    (ascending, contiguous); ``diffs`` is the ascending rotation chain
    for heights in ``(checkpoint_height + 1, target]`` — the FIRST proven
    height carries no diff because the client's trusted powers already
    apply to it.
    """

    checkpoint_height: int
    entries: List[ProofEntry] = field(default_factory=list)
    diffs: List[SetDiff] = field(default_factory=list)

    @property
    def target(self) -> int:
        return self.entries[-1].height if self.entries else self.checkpoint_height

    def to_wire(self) -> dict:
        return {
            "version": PROOF_WIRE_VERSION,
            "checkpoint": self.checkpoint_height,
            "entries": [e.to_wire() for e in self.entries],
            "diffs": [d.to_wire() for d in self.diffs],
        }

    @classmethod
    def from_wire(cls, rec: dict) -> "FinalityProof":
        version = rec.get("version") if isinstance(rec, dict) else None
        if version != PROOF_WIRE_VERSION:
            raise ProofError(f"unknown finality-proof version {version!r}")
        # Wire data is untrusted: every decode failure (missing key, bad
        # hex, non-numeric height, a corrupt nested proposal/cert blob)
        # surfaces as the documented ProofError contract, never a bare
        # KeyError/ValueError escaping the client's `except ProofError`.
        try:
            return cls(
                checkpoint_height=int(rec["checkpoint"]),
                entries=[
                    ProofEntry.from_wire(e) for e in rec.get("entries", ())
                ],
                diffs=[SetDiff.from_wire(d) for d in rec.get("diffs", ())],
            )
        except ProofError:
            raise
        except Exception as err:  # noqa: BLE001 - malformed untrusted bytes
            raise ProofError(
                f"malformed finality-proof wire record: "
                f"{type(err).__name__}: {err}"
            ) from err


def diff_chain(
    validators_for_height: Callable[[int], Mapping[bytes, int]],
    start: int,
    end: int,
) -> List[SetDiff]:
    """Rotation diffs for every height in ``[start, end]`` vs its
    predecessor (``start`` itself diffs against ``start - 1`` so a
    rotation landing exactly on a cache-chunk boundary is never lost).
    Heights with an unchanged set contribute nothing."""
    diffs: List[SetDiff] = []
    prev = dict(validators_for_height(start - 1)) if start > 1 else None
    for h in range(start, end + 1):
        cur = dict(validators_for_height(h))
        if prev is not None and cur != prev:
            diffs.append(
                SetDiff(
                    height=h,
                    added={
                        a: p
                        for a, p in cur.items()
                        if prev.get(a) != p
                    },
                    removed=tuple(sorted(a for a in prev if a not in cur)),
                )
            )
        prev = cur
    return diffs


def _check_powers(powers: Mapping[bytes, int], height: int) -> None:
    """Voting-power sanity at every hop of the walk.

    ``calculate_quorum`` over a non-positive total would yield a quorum
    of <= 0, and a quorum of <= 0 is satisfiable by ZERO seals — a
    served diff carrying negative or zero powers could otherwise turn
    the quorum check into a no-op for every height after it (the
    ``core/validator_manager.py::VotingPowerError`` invariant, enforced
    here against attacker-supplied wire data)."""
    total = 0
    for addr, power in powers.items():
        if not isinstance(power, int) or power <= 0:
            raise ProofError(
                f"height {height}: validator {addr.hex()[:16]} has "
                f"non-positive voting power {power!r}"
            )
        total += power
    if total <= 0:
        raise ProofError(f"height {height}: total voting power {total} <= 0")


def walk_sets(
    trusted_powers: Mapping[bytes, int],
    proof: FinalityProof,
    *,
    require_commitments: bool = False,
) -> Dict[int, Mapping[bytes, int]]:
    """Structurally validate ``proof`` and derive each height's validator
    set by walking the diff chain from the trusted checkpoint powers.

    Raises :class:`ProofError` on: empty range, a first entry that is not
    ``checkpoint + 1``, non-contiguous entries, out-of-range / unordered
    / duplicate diffs, a diff claimed for the first proven height (the
    trusted powers already apply there — a server cannot substitute the
    anchor set), or any hop whose powers are not strictly positive ints
    (a non-positive total would make ``calculate_quorum`` vacuous).

    Next-set commitment enforcement (ISSUE 20): when height ``h-1``'s
    proposal carries a next-set commitment frame
    (``lightsync/commitment.py``), the set derived for ``h`` must match
    the committed root — a fabricated diff AND an omitted rotation both
    fail here, because the root was sealed by ``h-1``'s commit quorum
    inside the proposal bytes.  With ``require_commitments=True`` a hop
    whose predecessor carries NO commitment is rejected too (the posture
    for chains producing commitments end to end); the first proven
    height needs none — the trusted anchor powers apply there.
    Cryptographic checks are the verifier's (``serve/server.py``); this
    walk is pure dict arithmetic plus one keccak per set change.
    """
    if not proof.entries:
        raise ProofError("finality proof carries no heights")
    first = proof.checkpoint_height + 1
    if proof.entries[0].height != first:
        raise ProofError(
            f"proof starts at height {proof.entries[0].height}, "
            f"checkpoint {proof.checkpoint_height} requires {first}"
        )
    heights = [e.height for e in proof.entries]
    if heights != list(range(first, first + len(heights))):
        raise ProofError("proof entries are not a contiguous height range")
    last = heights[-1]
    prev_h = first
    diff_by_height: Dict[int, SetDiff] = {}
    for d in proof.diffs:
        if not (first < d.height <= last):
            raise ProofError(
                f"set diff at height {d.height} outside ({first}, {last}]"
            )
        if d.height <= prev_h and diff_by_height:
            raise ProofError("set-diff chain is not strictly ascending")
        if d.height in diff_by_height:
            raise ProofError(f"duplicate set diff for height {d.height}")
        diff_by_height[d.height] = d
        prev_h = d.height
    sets: Dict[int, Mapping[bytes, int]] = {}
    cur: Mapping[bytes, int] = dict(trusted_powers)
    if not cur:
        raise ProofError("trusted checkpoint powers are empty")
    _check_powers(cur, first)
    cur_root: Optional[bytes] = None  # set_root(cur), computed on demand
    prev_entry: Optional[ProofEntry] = None
    for entry in proof.entries:
        h = entry.height
        d = diff_by_height.get(h)
        if d is not None:
            cur = d.apply(cur)
            if not cur:
                raise ProofError(f"set diff at height {h} empties the set")
            _check_powers(cur, h)
            cur_root = None
        if prev_entry is not None:
            committed = extract_next_set(prev_entry.proposal.raw_proposal)
            if committed is None:
                if require_commitments:
                    raise ProofError(
                        f"height {h}: the height {h - 1} proposal carries "
                        "no next-set commitment (required by this client)"
                    )
            else:
                if cur_root is None:
                    cur_root = set_root(cur)
                if committed != cur_root:
                    raise ProofError(
                        f"height {h}: served validator set does not match "
                        f"the next-set root the height {h - 1} quorum "
                        "sealed (fabricated or omitted rotation)"
                    )
        sets[h] = cur
        prev_entry = entry
    return sets


class ProofBuilder:
    """Assembles finality proofs from a node's served chain.

    ``source`` is any :class:`~go_ibft_tpu.chain.sync.SyncSource`
    (``ChainRunner`` serves its own chain through it);
    ``validators_for_height`` is the per-height snapshot seam the engine
    already uses everywhere.  The builder is pure assembly — it signs
    nothing and verifies nothing; the server's pre-serve self-check and
    the client's verification both run through ``serve/server.py``.
    """

    def __init__(
        self,
        source: SyncSource,
        validators_for_height: Callable[[int], Mapping[bytes, int]],
    ) -> None:
        self.source = source
        self.validators_for_height = validators_for_height

    def latest_height(self) -> int:
        return self.source.latest_height()

    def build_range(
        self, start: int, end: int
    ) -> Tuple[List[ProofEntry], List[SetDiff]]:
        """Entries + rotation diffs for heights ``[start, end]`` (diffs
        include ``start`` vs ``start - 1`` — the cache-chunk shape; see
        :func:`diff_chain`).  Raises :class:`ProofError` when the source
        cannot serve the full contiguous range."""
        if start < 1 or end < start:
            raise ProofError(f"invalid proof range [{start}, {end}]")
        blocks = self.source.get_blocks(start, end)
        expected = list(range(start, end + 1))
        if [b.height for b in blocks] != expected:
            raise ProofError(
                f"source cannot serve contiguous heights [{start}, {end}]"
            )
        entries = [ProofEntry.from_block(b) for b in blocks]
        return entries, diff_chain(self.validators_for_height, start, end)

    def build(self, checkpoint_height: int, target: int) -> FinalityProof:
        """One un-cached proof for ``(checkpoint_height, target]`` (the
        cache-and-coalesce path lives in :class:`~go_ibft_tpu.serve.
        server.ProofServer`; this is the direct seam tests and embedders
        without a server use)."""
        entries, diffs = self.build_range(checkpoint_height + 1, target)
        return FinalityProof(
            checkpoint_height=checkpoint_height,
            entries=entries,
            # The first proven height's set is the client's trusted
            # anchor; its diff (vs checkpoint) is never served.
            diffs=[d for d in diffs if d.height > checkpoint_height + 1],
        )
