"""Light-client proof serving: the read-heavy front end over ChainRunner.

Layer map (docs/SERVING.md):

* :mod:`go_ibft_tpu.serve.proof` — proof data model + builder
  (:class:`FinalityProof` = headers + quorum evidence + validator-set
  diff chain from a trusted checkpoint);
* :mod:`go_ibft_tpu.serve.cache` — canonical height-range chunk cache
  (:class:`ProofCache`: irreversible finality makes hot proofs a
  lookup);
* :mod:`go_ibft_tpu.serve.server` — :class:`ProofServer` (cache +
  stampede coalescing + pre-serve self-check) and
  :class:`ProofVerifier` (client-side verification with a shared
  signature-verdict cache and scheduler-coalesced fresh drains).
"""

from .cache import CachedChunk, ProofCache
from .proof import (
    FinalityProof,
    ProofBuilder,
    ProofEntry,
    ProofError,
    SetDiff,
    diff_chain,
    walk_sets,
)
from .server import (
    ProofServer,
    ProofVerifier,
    SigVerdictCache,
    any_signer_source,
)

__all__ = [
    "CachedChunk",
    "FinalityProof",
    "ProofBuilder",
    "ProofCache",
    "ProofEntry",
    "ProofError",
    "ProofServer",
    "ProofVerifier",
    "SetDiff",
    "SigVerdictCache",
    "any_signer_source",
    "diff_chain",
    "walk_sets",
]
