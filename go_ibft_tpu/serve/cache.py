"""ProofCache: canonical height-range chunks over irreversible finality.

IBFT finality never reverts, so a proof entry built for a finalized
height is immutable — the ideal cache load.  What makes naive caching
weak is the KEY: every client arrives with its own ``(checkpoint,
target)`` pair, and caching per request-range would give 1000 clients
1000 disjoint entries over the same blocks.  This cache normalizes to
**canonical chunks**: the chain is tiled into fixed ``chunk_heights``
windows aligned to height 1 (heights ``[1, C]``, ``[C+1, 2C]``, ...), a
request maps to the chunks covering it, and the server stitches the
answer from chunk entries — so overlapping requests share every full
chunk they touch, and the partial tail chunk (still growing; not yet
canonical) is the only per-request work.

Bounded memory: at most ``max_chunks`` chunks, LRU-evicted (serving old
history to a cold archive walker cannot push the hot head chunks out
faster than they are re-used).  Hit/miss/eviction counters feed
``stats()`` and the ``serve.*`` metrics — the evidence bench config #12
records.

Thread safety: one lock around the OrderedDict; chunk payloads are
immutable after :meth:`put` (the server never mutates a cached entry —
stitching copies the LIST, not the entries).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..utils import metrics
from .proof import ProofEntry, SetDiff

__all__ = [
    "CachedChunk",
    "ProofCache",
    "SERVE_CACHE_HITS_KEY",
    "SERVE_CACHE_MISSES_KEY",
    "SERVE_CACHE_EVICTIONS_KEY",
]

SERVE_CACHE_HITS_KEY = ("go-ibft", "serve", "cache_hits")
SERVE_CACHE_MISSES_KEY = ("go-ibft", "serve", "cache_misses")
SERVE_CACHE_EVICTIONS_KEY = ("go-ibft", "serve", "cache_evictions")


@dataclass(frozen=True)
class CachedChunk:
    """One canonical chunk: entries for ``[start, end]`` plus the rotation
    diffs for the same heights (each vs its predecessor, ``start``
    included — so a rotation on the chunk boundary survives stitching)."""

    start: int
    end: int
    entries: Tuple[ProofEntry, ...]
    diffs: Tuple[SetDiff, ...]


class ProofCache:
    """LRU cache of canonical proof chunks, keyed by chunk start height."""

    def __init__(self, *, chunk_heights: int = 64, max_chunks: int = 256):
        if chunk_heights < 1 or max_chunks < 1:
            raise ValueError("cache bounds must be >= 1")
        self.chunk_heights = chunk_heights
        self.max_chunks = max_chunks
        self._lock = threading.Lock()
        self._chunks: "OrderedDict[int, CachedChunk]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- canonical geometry ----------------------------------------------

    def chunk_start(self, height: int) -> int:
        """Start height of the canonical chunk containing ``height``
        (heights are 1-based; chunks align to height 1)."""
        return ((height - 1) // self.chunk_heights) * self.chunk_heights + 1

    def chunk_starts(self, start: int, end: int) -> List[int]:
        """Canonical chunk starts covering ``[start, end]``."""
        first = self.chunk_start(start)
        return list(
            range(first, end + 1, self.chunk_heights)
        )

    # -- lookup / insert -------------------------------------------------

    def get(self, chunk_start: int) -> Optional[CachedChunk]:
        with self._lock:
            chunk = self._chunks.get(chunk_start)
            if chunk is None:
                self.misses += 1
                metrics.inc_counter(SERVE_CACHE_MISSES_KEY)
                return None
            self._chunks.move_to_end(chunk_start)
            self.hits += 1
            metrics.inc_counter(SERVE_CACHE_HITS_KEY)
            return chunk

    def peek(self, chunk_start: int) -> Optional[CachedChunk]:
        """Lookup without touching hit/miss counters or LRU order (the
        server's under-build-lock re-check: a stampede loser finding the
        winner's chunk is not a second cold miss)."""
        with self._lock:
            return self._chunks.get(chunk_start)

    def put(
        self,
        chunk_start: int,
        entries: List[ProofEntry],
        diffs: List[SetDiff],
    ) -> CachedChunk:
        """Insert one FULL canonical chunk (``chunk_heights`` entries
        starting exactly at a canonical boundary — partial tail windows
        are never cached: they are still growing and would poison
        stitching once the chain passes them)."""
        if chunk_start != self.chunk_start(chunk_start):
            raise ValueError(
                f"chunk start {chunk_start} is not on a canonical boundary"
            )
        if len(entries) != self.chunk_heights:
            raise ValueError(
                f"chunk must carry exactly {self.chunk_heights} entries, "
                f"got {len(entries)}"
            )
        chunk = CachedChunk(
            start=chunk_start,
            end=chunk_start + self.chunk_heights - 1,
            entries=tuple(entries),
            diffs=tuple(diffs),
        )
        with self._lock:
            self._chunks[chunk_start] = chunk
            self._chunks.move_to_end(chunk_start)
            while len(self._chunks) > self.max_chunks:
                self._chunks.popitem(last=False)
                self.evictions += 1
                metrics.inc_counter(SERVE_CACHE_EVICTIONS_KEY)
        return chunk

    def __len__(self) -> int:
        with self._lock:
            return len(self._chunks)

    def clear(self) -> None:
        with self._lock:
            self._chunks.clear()

    # -- evidence --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            chunks = len(self._chunks)
            hits, misses, evictions = self.hits, self.misses, self.evictions
        lookups = hits + misses
        return {
            "chunks": chunks,
            "chunk_heights": self.chunk_heights,
            "max_chunks": self.max_chunks,
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / lookups, 3) if lookups else None,
            "evictions": evictions,
        }
