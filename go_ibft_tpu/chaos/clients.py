"""Client-side chaos: adversarial HTTP clients for the proof-API fleet.

The transport/verify wrappers in this package attack the consensus
plane; these attack the SERVE plane the way the open internet does —
from outside the process, over real sockets, against the node's proof
API (:mod:`go_ibft_tpu.node.proof_api`).  Two shapes, both seeded and
replayable:

* :class:`SlowlorisClient` — opens connections and trickles a partial
  HTTP request a few bytes at a time, forever.  A correct server cuts
  each one off at its header timeout; a thread-per-connection server
  without one would bleed capacity until the honest fleet starves.
  The wrapper counts how many of its sockets the server cut — the
  fleet harness asserts it was ALL of them.
* :class:`ChurningClient` — connect / one request / close in a tight
  loop, the connection-churn load pattern (mobile clients, NAT
  timeouts, crash-looping SDKs).  Exercises accept-path overhead and
  the connection cap's 503 shedding.

Counting rides the package convention — ``("go-ibft", "chaos", kind)``
counters + ``chaos.<kind>`` trace instants — and every decision stream
derives from one seed, so :func:`fleet_replay_line` emits the standard
``CHAOS-REPLAY`` artifact (``scripts/chaos_replay.py --line`` replays
the client plan against a fresh in-process server and re-verifies the
schedule digest).
"""

from __future__ import annotations

import hashlib
import json
import random
import socket
import threading
from typing import Dict, List

from ..obs import trace
from ..utils import metrics

__all__ = [
    "ChurningClient",
    "SlowlorisClient",
    "client_schedule_digest",
    "fleet_replay_line",
]

SLOWLORIS_KEY = ("go-ibft", "chaos", "client_slowloris_bytes")
CHURN_KEY = ("go-ibft", "chaos", "client_churn_conns")

# One partial request, fed byte-by-byte: a legitimate-looking prefix so
# the server cannot tell it from a slow phone until the timeout trips.
_SLOWLORIS_PREFIX = (
    b"GET /proof?checkpoint=0 HTTP/1.1\r\n"
    b"Host: fleet\r\n"
    b"User-Agent: slow-client/0.1\r\n"
    b"X-Padding: "
)


def _stream(seed: int, client_id: int, kind: str) -> random.Random:
    """Per-client decision stream: one seed fans out deterministically."""
    digest = hashlib.sha256(
        b"%d|%s|%d" % (seed, kind.encode(), client_id)
    ).digest()
    return random.Random(int.from_bytes(digest[:8], "big"))


class SlowlorisClient:
    """``conns`` sockets trickling partial requests at a target.

    ``run(stop)`` blocks until ``stop`` is set (the harness drives it on
    a thread); :attr:`stats` reports opened/cut counts.  Every sleep and
    chunk size comes from the seeded stream — two runs with one seed
    produce the identical byte schedule.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        seed: int,
        client_id: int = 0,
        conns: int = 4,
        trickle_interval_s: float = 0.25,
    ) -> None:
        self.host = host
        self.port = port
        self.conns = conns
        self.trickle_interval_s = trickle_interval_s
        self._rng = _stream(seed, client_id, "slowloris")
        self.stats: Dict[str, int] = {
            "opened": 0,
            "cut_by_server": 0,
            "bytes_sent": 0,
            "connect_failures": 0,
        }

    def run(self, stop: threading.Event) -> Dict[str, int]:
        socks: List[socket.socket] = []
        sent: List[int] = []
        for _ in range(self.conns):
            try:
                s = socket.create_connection(
                    (self.host, self.port), timeout=5.0
                )
                s.setblocking(True)
                s.settimeout(0.5)
                socks.append(s)
                sent.append(0)
                self.stats["opened"] += 1
            except OSError:
                self.stats["connect_failures"] += 1
        trace.instant("chaos.client_slowloris", conns=len(socks))
        alive = list(range(len(socks)))
        while alive and not stop.is_set():
            for idx in list(alive):
                s = socks[idx]
                # 1-3 bytes per tick: far below any byte-rate heuristic,
                # exactly the pathology the header timeout exists for.
                n = self._rng.randint(1, 3)
                offset = sent[idx]
                chunk = (_SLOWLORIS_PREFIX * 64)[offset : offset + n]
                try:
                    s.send(chunk)
                    sent[idx] += n
                    self.stats["bytes_sent"] += n
                    metrics.inc_counter(SLOWLORIS_KEY, n)
                except OSError:
                    # Server cut us off — the defense worked.
                    self.stats["cut_by_server"] += 1
                    alive.remove(idx)
                    continue
                # A FIN from the server also means we were cut.
                try:
                    if s.recv(4096) == b"":
                        self.stats["cut_by_server"] += 1
                        alive.remove(idx)
                except socket.timeout:
                    pass
                except OSError:
                    self.stats["cut_by_server"] += 1
                    alive.remove(idx)
            stop.wait(
                self.trickle_interval_s * self._rng.uniform(0.5, 1.5)
            )
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        return dict(self.stats)


class ChurningClient:
    """Connect / one ``GET /head`` / close, in a seeded tight loop."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        seed: int,
        client_id: int = 0,
        interval_s: float = 0.05,
    ) -> None:
        self.host = host
        self.port = port
        self.interval_s = interval_s
        self._rng = _stream(seed, client_id, "churn")
        self.stats: Dict[str, int] = {
            "churns": 0,
            "responses": 0,
            "rejected_503": 0,
            "errors": 0,
        }

    def run(self, stop: threading.Event) -> Dict[str, int]:
        trace.instant("chaos.client_churn")
        while not stop.is_set():
            try:
                s = socket.create_connection(
                    (self.host, self.port), timeout=5.0
                )
                s.settimeout(5.0)
                s.send(
                    b"GET /head HTTP/1.1\r\nHost: fleet\r\n"
                    b"Connection: close\r\n\r\n"
                )
                data = b""
                while b"\r\n\r\n" not in data and len(data) < 65536:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    data += chunk
                s.close()
                self.stats["churns"] += 1
                metrics.inc_counter(CHURN_KEY)
                if data.startswith(b"HTTP/1.1 200"):
                    self.stats["responses"] += 1
                elif data.startswith(b"HTTP/1.1 503"):
                    self.stats["rejected_503"] += 1
                elif not data:
                    self.stats["errors"] += 1
            except OSError:
                self.stats["errors"] += 1
            stop.wait(self.interval_s * self._rng.uniform(0.5, 1.5))
        return dict(self.stats)


# ---------------------------------------------------------------------------
# replay contract
# ---------------------------------------------------------------------------


def client_schedule_digest(
    seed: int, churn_clients: int, slowloris_clients: int, n: int = 64
) -> str:
    """Fingerprint of every client's first ``n`` decisions — the
    schedule half of the fleet CHAOS-REPLAY line.  Mirrors
    ``FaultInjector.schedule_digest``: replaying with the same seed MUST
    rebuild the same digest or the replay is not the same scenario."""
    h = hashlib.sha256()
    for kind, count in (
        ("churn", churn_clients),
        ("slowloris", slowloris_clients),
    ):
        for client_id in range(count):
            rng = _stream(seed, client_id, kind)
            h.update(kind.encode())
            h.update(client_id.to_bytes(2, "big"))
            for _ in range(n):
                if kind == "slowloris":
                    h.update(rng.randint(1, 3).to_bytes(1, "big"))
                h.update(int(rng.uniform(0.5, 1.5) * 1e6).to_bytes(4, "big"))
    return h.hexdigest()[:16]


def fleet_replay_line(seed: int, fleet_config: dict) -> str:
    """The fleet run's ``CHAOS-REPLAY`` artifact line.

    ``fleet_config`` must carry ``churn_clients``/``slowloris_clients``
    (the digest inputs) plus whatever shape fields the harness wants
    reproduced (nodes/heights/connections).  Parsed back by
    ``go_ibft_tpu.sim.parse_replay_line``; dispatched by
    ``scripts/chaos_replay.py --line`` on the ``fleet`` key.
    """
    digest = client_schedule_digest(
        seed,
        int(fleet_config.get("churn_clients", 0)),
        int(fleet_config.get("slowloris_clients", 0)),
    )
    blob = json.dumps({"fleet": fleet_config}, sort_keys=True)
    return f"CHAOS-REPLAY seed={seed} schedule={digest} config={blob}"
