"""Chaos-injection harness: deterministic faults at the engine's seams.

BFT deployments are defined by how they behave under loss and adversarial
input; this package makes that testable on every commit.  A seed-driven
:class:`FaultInjector` produces a replayable fault schedule (same seed =>
byte-identical decisions, pinned in tests/test_chaos.py) and a family of
wrappers applies it at the three seams the engine already exposes:

* transports / deliver callables — drops, delays, reordering, duplication,
  wire-encoding bit-flips (:class:`ChaoticDeliver`/:class:`ChaoticTransport`);
* batch verifiers and crypto backends — slow verifies and simulated XLA
  ``RuntimeError`` on dispatch (:class:`ChaoticVerifier`/:class:`ChaoticBackend`);
* pipeline dispatch callables (:func:`chaotic_dispatch`);
* chain-layer hooks — seeded kill -9 points for crash/restart suites
  (:class:`CrashRestart` raising :class:`SimulatedCrash`), recovered via
  ``ChainRunner.recover()`` WAL replay;
* serve-plane clients — adversarial HTTP clients (connection churn +
  slowloris) for the multi-process fleet harness
  (:class:`ChurningClient`/:class:`SlowlorisClient`, :mod:`.clients`).

Any chaos-test failure prints a ``CHAOS-REPLAY`` line with the seed and
schedule digest (:func:`replay_on_failure`); ``scripts/chaos_replay.py``
re-runs the scenario from that seed.  The degraded-mode machinery these
faults exercise lives in :mod:`go_ibft_tpu.verify` (quarantine bisection +
circuit breaker); see docs/ROBUSTNESS.md for the full fault model.
"""

from .clients import (
    ChurningClient,
    SlowlorisClient,
    client_schedule_digest,
    fleet_replay_line,
)
from .injector import (
    FaultConfig,
    FaultInjector,
    InjectedDeviceError,
    TransportFault,
    VerifyFault,
    replay_on_failure,
)
from .wrappers import (
    ChaoticBackend,
    ChaoticDeliver,
    ChaoticTransport,
    ChaoticVerifier,
    CrashRestart,
    SimulatedCrash,
    chaotic_dispatch,
    corrupt_message,
)

__all__ = [
    "CrashRestart",
    "SimulatedCrash",
    "FaultConfig",
    "FaultInjector",
    "InjectedDeviceError",
    "TransportFault",
    "VerifyFault",
    "replay_on_failure",
    "ChaoticBackend",
    "ChaoticDeliver",
    "ChaoticTransport",
    "ChaoticVerifier",
    "ChurningClient",
    "SlowlorisClient",
    "chaotic_dispatch",
    "client_schedule_digest",
    "corrupt_message",
    "fleet_replay_line",
]
