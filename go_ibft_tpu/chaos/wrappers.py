"""Chaos wrappers: transports, verifiers, backends, pipeline dispatches.

Each wrapper interposes a :class:`~go_ibft_tpu.chaos.injector.FaultInjector`
between a real component and its caller, applying that site's deterministic
fault stream.  Wrappers forward everything they do not fault-gate, so they
are drop-in at the same seams the engine already has: ``Transport``
(one-method multicast), per-receiver deliver callables,
``BatchVerifier``/crypto backends, and
:class:`~go_ibft_tpu.verify.pipeline.VerifyPipeline` dispatch callables.

Every injected fault is counted under ``("go-ibft", "chaos", <kind>)`` so
soak tests can assert that chaos actually happened (a soak that injected
nothing proves nothing).
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, List, Optional

from ..messages.wire import IbftMessage
from ..obs import trace
from ..utils import metrics
from .injector import FaultInjector

_CHAOS = "chaos"


def _count(kind: str, n: int = 1, site: Optional[str] = None) -> None:
    metrics.inc_counter(("go-ibft", _CHAOS, kind), n)
    # Injection sites land on the flight-recorder timeline: a chaotic soak
    # trace shows WHERE each fault hit relative to the round phases.
    trace.instant("chaos." + kind, site=site)


def corrupt_message(message: IbftMessage, bit: int) -> Optional[IbftMessage]:
    """Flip one bit of the message's wire encoding and re-decode.

    Returns the mutated COPY (never touches the original — a loopback
    multicast shares one object across receivers), or ``None`` when the
    flip produced undecodable bytes (a lossy link eating the frame).
    """
    data = bytearray(message.encode())
    if not data:
        return None
    data[(bit // 8) % len(data)] ^= 1 << (bit % 8)
    try:
        return IbftMessage.decode(bytes(data))
    except Exception:  # noqa: BLE001 - garbage frames drop, like real links
        return None


class ChaoticDeliver:
    """Wrap one receiver's deliver callable with transport faults.

    Drop/delay/duplicate/reorder/bit-flip per delivery, drawn from the
    injector's ``site`` stream.  Delay and reorder need a running asyncio
    loop (``loop.call_later``); without one they degrade to in-order
    synchronous delivery (drop/duplicate/corrupt still apply), so the
    wrapper is safe in plain synchronous tests too.

    Reordering holds the message back and releases it after the NEXT
    delivery at this site (a held message is also flushed by a timer so a
    reordered tail message cannot be starved forever).
    """

    def __init__(
        self,
        deliver: Callable[[IbftMessage], None],
        injector: FaultInjector,
        site: str,
        *,
        flush_after_s: float = 0.02,
    ) -> None:
        self._deliver = deliver
        self._injector = injector
        self.site = site
        self._held: List[IbftMessage] = []
        self._flush_after_s = flush_after_s

    @staticmethod
    def _loop() -> Optional[asyncio.AbstractEventLoop]:
        try:
            return asyncio.get_running_loop()
        except RuntimeError:
            return None

    def _flush_held(self) -> None:
        held, self._held = self._held, []
        for m in held:
            self._deliver(m)

    def __call__(self, message: IbftMessage) -> None:
        fault = self._injector.transport_fault(self.site)
        if fault.drop:
            _count("dropped", site=self.site)
            return
        if fault.corrupt_bit >= 0:
            _count("corrupted", site=self.site)
            message = corrupt_message(message, fault.corrupt_bit)
            if message is None:  # undecodable frame: the link ate it
                return
        copies = [message, message] if fault.duplicate else [message]
        if fault.duplicate:
            _count("duplicated", site=self.site)
        loop = self._loop()
        if loop is None:
            self._flush_held()
            for m in copies:
                self._deliver(m)
            return
        if fault.reorder:
            _count("reordered", site=self.site)
            self._held.extend(copies)
            loop.call_later(self._flush_after_s, self._flush_held)
            return
        if fault.delay_s > 0:
            _count("delayed", site=self.site)
            for m in copies:
                loop.call_later(fault.delay_s, self._deliver, m)
        else:
            for m in copies:
                self._deliver(m)
        # Release anything held back by an earlier reorder AFTER this
        # delivery — the swap that actually reorders.
        if self._held:
            self._flush_held()


class ChaoticTransport:
    """Wrap a whole ``Transport`` (the reference's one-method seam): every
    ``multicast`` passes through one :class:`ChaoticDeliver` gate before
    reaching the inner transport."""

    def __init__(
        self, inner, injector: FaultInjector, site: str = "transport"
    ) -> None:
        self.inner = inner
        self._gate = ChaoticDeliver(inner.multicast, injector, site)

    def multicast(self, message: IbftMessage) -> None:
        self._gate(message)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class ChaoticVerifier:
    """Wrap a ``BatchVerifier`` (or crypto-path verifier) with device
    faults: each drain may run slow (``slow_verify_s``) or raise the
    injector's simulated XLA dispatch ``RuntimeError``.

    This is what a dead/flaky accelerator looks like to the engine — the
    exact input :class:`~go_ibft_tpu.verify.ResilientBatchVerifier` and the
    circuit breaker are built to absorb.  Everything not fault-gated
    (``note_round``, ``warmup``, ``supports_fused``, ``quarantine``, the
    certify entry points, ...) forwards to the inner verifier untouched.
    """

    def __init__(self, inner, injector: FaultInjector, site: str = "verify") -> None:
        self.inner = inner
        self._injector = injector
        self.site = site

    def _gate(self) -> None:
        fault = self._injector.verify_fault(self.site)
        if fault.slow_s > 0:
            _count("slow_verifies", site=self.site)
            time.sleep(fault.slow_s)
        if fault.device_error:
            _count("device_errors", site=self.site)
            raise self._injector.device_error(self.site)

    def verify_senders(self, msgs):
        self._gate()
        return self.inner.verify_senders(msgs)

    def verify_committed_seals(self, proposal_hash, seals, height):
        self._gate()
        return self.inner.verify_committed_seals(proposal_hash, seals, height)

    def verify_seal_lanes(self, lanes, height):
        # The cross-height sync drain passes the same device-fault gate as
        # every other dispatch (without this explicit hop, __getattr__
        # would forward it to the inner verifier chaos-free).
        self._gate()
        return self.inner.verify_seal_lanes(lanes, height)

    def certify_senders(self, msgs, height, threshold=None):
        self._gate()
        return self.inner.certify_senders(msgs, height, threshold)

    def certify_seals(self, proposal_hash, seals, height, threshold=None):
        self._gate()
        return self.inner.certify_seals(proposal_hash, seals, height, threshold)

    def certify_round(self, msgs, proposal_hash, seals, height, prepare_threshold=None):
        self._gate()
        return self.inner.certify_round(
            msgs, proposal_hash, seals, height, prepare_threshold
        )

    def __getattr__(self, name):
        return getattr(self.inner, name)


class ChaoticBackend:
    """Wrap an embedder crypto backend: the per-message verification
    predicates (``is_valid_validator``, ``is_valid_committed_seal``) pass
    the same slow/error gate as the batched drains; every other backend
    method forwards untouched."""

    def __init__(self, inner, injector: FaultInjector, site: str = "backend") -> None:
        self.inner = inner
        self._injector = injector
        self.site = site

    def _gate(self) -> None:
        fault = self._injector.verify_fault(self.site)
        if fault.slow_s > 0:
            _count("slow_verifies", site=self.site)
            time.sleep(fault.slow_s)
        if fault.device_error:
            _count("device_errors", site=self.site)
            raise self._injector.device_error(self.site)

    def is_valid_validator(self, msg):
        self._gate()
        return self.inner.is_valid_validator(msg)

    def is_valid_committed_seal(self, proposal_hash, committed_seal, height=None):
        self._gate()
        return self.inner.is_valid_committed_seal(
            proposal_hash, committed_seal, height
        )

    def __getattr__(self, name):
        return getattr(self.inner, name)


class SimulatedCrash(BaseException):
    """The kill -9 stand-in a :class:`CrashRestart` raises at its seeded
    point.  A ``BaseException`` deliberately — a real kill is not
    catchable by the engine's advisory ``except Exception`` guards, so
    neither is its simulation; it unwinds straight to the test harness.
    Carries the seed + site so a crash-test failure is replayable like
    every other chaos artifact."""


class CrashRestart:
    """Seeded kill-point injection for crash/restart suites.

    Arms a deterministic crash at the Nth hit of a named site (N drawn
    once from the injector's per-site stream, so the schedule is
    byte-stable per seed).  Typical wiring: wrap a chain-layer hook —
    ``IBFT.on_lock`` (die mid-round holding a fresh PC) or ``on_finalize``
    (die between the WAL append and the store prune) — with
    :meth:`wrap`; when the crash fires the wrapper raises
    :class:`SimulatedCrash` AFTER forwarding to the real hook (the process
    died after the durable step, the kill -9 shape) or BEFORE it
    (``before=True`` — died short of durability), and the harness treats
    the node as dead: cancel its tasks, drop its in-memory state, rebuild
    from the WAL via ``ChainRunner.recover()``.
    """

    def __init__(
        self,
        injector: FaultInjector,
        site: str,
        *,
        lo: int = 1,
        hi: int = 4,
    ) -> None:
        self._injector = injector
        self.site = site
        self.crash_at = injector.crash_after(site, lo, hi)
        self.hits = 0
        self.fired = False

    def check(self) -> None:
        """Count one event; raise :class:`SimulatedCrash` at the kill
        point (exactly once — a restarted node re-arming the same object
        keeps running)."""
        self.hits += 1
        if not self.fired and self.hits >= self.crash_at:
            self.fired = True
            _count("crashes", site=self.site)
            raise SimulatedCrash(
                f"chaos: injected crash at event {self.hits} "
                f"(seed={self._injector.seed}, site={self.site})"
            )

    def wrap(self, hook: Optional[Callable], *, before: bool = False):
        """Wrap a hook callable with this kill point.

        ``before=False`` (default): the real hook runs first, THEN the
        crash fires — the durable step completed, the process died on the
        way out.  ``before=True``: the crash pre-empts the hook — death
        short of durability.  A ``None`` hook is allowed (the crash point
        alone is the wrapped behavior).
        """

        def wrapped(*args, **kwargs):
            if before:
                self.check()
            result = hook(*args, **kwargs) if hook is not None else None
            if not before:
                self.check()
            return result

        return wrapped


def chaotic_dispatch(
    dispatch: Callable, injector: FaultInjector, site: str = "pipeline"
) -> Callable:
    """Wrap a :class:`~go_ibft_tpu.verify.pipeline.VerifyPipeline` dispatch
    callable: each dispatched item may stall or raise the simulated device
    error, exactly where a real XLA dispatch would."""

    def wrapped(packed):
        fault = injector.verify_fault(site)
        if fault.slow_s > 0:
            _count("slow_verifies", site=site)
            time.sleep(fault.slow_s)
        if fault.device_error:
            _count("device_errors", site=site)
            raise injector.device_error(site)
        return dispatch(packed)

    return wrapped
