"""Deterministic, seed-driven fault injection.

A :class:`FaultInjector` is the single source of every chaos decision in a
run.  Decisions are drawn from **per-site** PRNG streams — each site (a
named injection point like ``deliver:node-3`` or ``verify:node-0``) gets
its own ``random.Random`` seeded from ``(seed, site)`` — so the decision
sequence at any site is a pure function of the seed, independent of how
other sites interleave.  Every event consumes a FIXED number of draws,
which makes the schedule **byte-identical across runs**:
:meth:`FaultInjector.schedule_bytes` re-derives a site's first N decisions
from scratch and two injectors with the same seed produce the same bytes
(the determinism contract pinned by tests/test_chaos.py).

Reproduction: a failing chaos test prints one ``CHAOS-REPLAY`` artifact
line carrying the seed, config, and schedule digest
(:func:`replay_on_failure`); ``scripts/chaos_replay.py --seed N`` re-runs
the soak scenario under exactly that schedule.
"""

from __future__ import annotations

import hashlib
import json
import random
from contextlib import contextmanager
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterator, NamedTuple


@dataclass(frozen=True)
class FaultConfig:
    """Per-event fault probabilities and magnitudes (all default off).

    Rates are independent per event: one delivery may be dropped, another
    delayed AND duplicated.  ``device_error_burst`` is deterministic-first:
    when > 0, the first N device dispatches at a site fail regardless of
    ``device_error_rate`` — the shape the circuit-breaker suites need (a
    dead device that comes back) without tuning rates.
    """

    drop_rate: float = 0.0
    delay_rate: float = 0.0
    max_delay_s: float = 0.0
    reorder_rate: float = 0.0
    duplicate_rate: float = 0.0
    corrupt_rate: float = 0.0
    slow_verify_rate: float = 0.0
    slow_verify_s: float = 0.0
    device_error_rate: float = 0.0
    device_error_burst: int = 0


class TransportFault(NamedTuple):
    """One delivery's fate (fixed draw count: 6 uniforms per event)."""

    drop: bool
    delay_s: float  # 0.0 = deliver now
    duplicate: bool
    reorder: bool
    corrupt_bit: int  # -1 = intact; else the bit index to flip (mod size)


class VerifyFault(NamedTuple):
    """One verify dispatch's fate (fixed draw count: 2 uniforms)."""

    device_error: bool
    slow_s: float  # 0.0 = full speed


class FaultInjector:
    """Replayable fault oracle: seed + config -> every chaos decision.

    Thread-safe per site is NOT promised — chaos runs are single event
    loop by design (determinism would die with racing draws).
    """

    def __init__(self, seed: int, config: FaultConfig = FaultConfig()):
        self.seed = int(seed)
        self.config = config
        self._streams: Dict[str, random.Random] = {}
        self._device_calls: Dict[str, int] = {}

    # -- per-site streams ----------------------------------------------

    def _stream(self, site: str) -> random.Random:
        rng = self._streams.get(site)
        if rng is None:
            # Seeding with a string hashes it through sha512 (random's
            # version-2 str seeding) — stable across processes, unlike
            # hash().
            rng = random.Random(f"{self.seed}:{site}")
            self._streams[site] = rng
        return rng

    # -- decision draws (fixed draw count per event) --------------------

    def transport_fault(self, site: str) -> TransportFault:
        """Fate of one delivery at ``site``.  Always 6 draws."""
        rng = self._stream(site)
        c = self.config
        u_drop, u_delay, u_amount, u_dup, u_reorder, u_corrupt = (
            rng.random() for _ in range(6)
        )
        return TransportFault(
            drop=u_drop < c.drop_rate,
            delay_s=(u_amount * c.max_delay_s) if u_delay < c.delay_rate else 0.0,
            duplicate=u_dup < c.duplicate_rate,
            reorder=u_reorder < c.reorder_rate,
            corrupt_bit=(
                int(u_amount * (1 << 16)) if u_corrupt < c.corrupt_rate else -1
            ),
        )

    def verify_fault(self, site: str) -> VerifyFault:
        """Fate of one verify dispatch at ``site``.  Always 2 draws, plus
        the deterministic ``device_error_burst`` prefix."""
        rng = self._stream(site)
        c = self.config
        u_err, u_slow = rng.random(), rng.random()
        calls = self._device_calls.get(site, 0)
        self._device_calls[site] = calls + 1
        burst = calls < c.device_error_burst
        return VerifyFault(
            device_error=burst or u_err < c.device_error_rate,
            slow_s=c.slow_verify_s if u_slow < c.slow_verify_rate else 0.0,
        )

    def crash_after(self, site: str, lo: int = 1, hi: int = 8) -> int:
        """Seeded kill-point: the 1-based event count at which a
        :class:`~go_ibft_tpu.chaos.wrappers.CrashRestart` armed at ``site``
        fires.  One draw, so schedules stay byte-stable."""
        return self._stream(site).randint(lo, hi)

    def device_error(self, site: str) -> "InjectedDeviceError":
        """The exception a chaotic dispatch raises — mimics an XLA
        ``RuntimeError`` surfacing from a dead device, and names the seed
        so any traceback is replayable on its own."""
        return InjectedDeviceError(
            f"chaos: injected device error on dispatch "
            f"(seed={self.seed}, site={site})"
        )

    # -- replayable schedule -------------------------------------------

    def schedule_bytes(self, site: str, n: int, kind: str = "transport") -> bytes:
        """The first ``n`` decisions at ``site``, serialized — derived from
        a FRESH stream, so the result is independent of live draws already
        made.  Same seed + config + site => byte-identical output (the
        chaos determinism contract)."""
        saved_stream = self._streams.pop(site, None)
        saved_calls = self._device_calls.pop(site, None)
        out = bytearray()
        try:
            for _ in range(n):
                if kind == "transport":
                    f = self.transport_fault(site)
                    out.append(
                        (f.drop << 0)
                        | (f.duplicate << 1)
                        | (f.reorder << 2)
                        | ((f.corrupt_bit >= 0) << 3)
                        | ((f.delay_s > 0) << 4)
                    )
                    out += int(f.delay_s * 1e6).to_bytes(4, "big")
                    out += (f.corrupt_bit & 0xFFFF).to_bytes(2, "big")
                else:
                    f = self.verify_fault(site)
                    out.append((f.device_error << 0) | ((f.slow_s > 0) << 1))
        finally:
            if saved_stream is not None:
                self._streams[site] = saved_stream
            else:
                self._streams.pop(site, None)
            if saved_calls is not None:
                self._device_calls[site] = saved_calls
            else:
                self._device_calls.pop(site, None)
        return bytes(out)

    def schedule_digest(self, sites: Iterator[str] = ("transport", "verify"), n: int = 256) -> str:
        """Short hex digest of the first ``n`` decisions at each site —
        the schedule fingerprint carried on CHAOS-REPLAY lines."""
        h = hashlib.sha256()
        for site in sites:
            kind = "verify" if site.startswith("verify") else "transport"
            h.update(site.encode())
            h.update(self.schedule_bytes(site, n, kind=kind))
        return h.hexdigest()[:16]

    def describe(self) -> dict:
        return {
            "seed": self.seed,
            "config": asdict(self.config),
            "schedule_digest": self.schedule_digest(),
        }

    def replay_line(self) -> str:
        """The one-line replay artifact printed on chaos-test failure."""
        d = self.describe()
        return (
            f"CHAOS-REPLAY seed={d['seed']} "
            f"schedule={d['schedule_digest']} "
            f"config={json.dumps(d['config'], sort_keys=True)}"
        )


class InjectedDeviceError(RuntimeError):
    """The simulated XLA dispatch failure (RuntimeError subclass, exactly
    what jax surfaces when a device dies mid-program)."""


@contextmanager
def replay_on_failure(injector: FaultInjector):
    """Print the injector's CHAOS-REPLAY artifact line when the body
    raises (assertion or crash), then re-raise.

    pytest captures stdout and replays it for failing tests, so the seed
    and schedule digest land in the failure report — the flake is
    replayable via ``scripts/chaos_replay.py --seed N``."""
    try:
        yield injector
    except BaseException:
        print(injector.replay_line(), flush=True)
        raise
