"""Minimal complete embedder: a 4-validator chain finalizing 3 blocks.

What a user of the reference wires through Backend/Transport/Logger
(go-ibft README "Usage" + core/backend.go:69-85), done with this
framework's batteries included:

* keys + signing + verification  -> crypto.ECDSABackend
* network                        -> core.LoopbackTransport (swap for
                                    net.GrpcTransport across hosts, or
                                    net.IciLockstepTransport on a mesh)
* the TPU data plane             -> verify.DeviceBatchVerifier (optional;
                                    the engine runs the sequential host
                                    path without it)

Run: ``python examples/minimal_embedder.py [--device]``
"""

import argparse
import asyncio
import sys
from typing import Optional

sys.path.insert(0, ".")

# noqa-justified: sys.path setup must precede package imports when run as a
# script from the repo root.
from go_ibft_tpu.core import IBFT, LoopbackTransport  # noqa: E402
from go_ibft_tpu.crypto import PrivateKey  # noqa: E402
from go_ibft_tpu.crypto.backend import ECDSABackend  # noqa: E402


class StdoutLogger:
    def info(self, msg, *args):
        print(f"[info ] {msg} {args if args else ''}")

    def debug(self, msg, *args):
        pass

    def error(self, msg, *args):
        print(f"[error] {msg} {args if args else ''}")


def build_cluster(
    n: int,
    use_device: bool,
    use_bls: bool = False,
    use_mesh: bool = False,
    use_aggregate: bool = False,
    use_speculate: bool = False,
    commit_next_set: bool = False,
):
    # 1. Validator identities and the (static) voting-power map.
    keys = [PrivateKey.from_seed(b"example-validator-%d" % i) for i in range(n)]
    powers = {k.address: 1 for k in keys}
    validators = ECDSABackend.static_validators(powers)

    use_bls = use_bls or use_aggregate
    certifier = hub = None
    if use_bls:
        # BLS committed seals: ECDSA envelopes + BLS G2 seals, so a whole
        # COMMIT quorum certifies with ONE pairing (aggregate verification).
        from go_ibft_tpu.crypto import bls as hbls
        from go_ibft_tpu.crypto.bls_backend import HybridBLSBackend

        bls_keys = [
            hbls.BLSPrivateKey.from_seed(b"example-bls-%d" % i) for i in range(n)
        ]
        if use_aggregate:
            # Production posture: pubkeys enter the aggregation set ONLY
            # with a proof of possession (rogue-key defense), and COMMIT
            # dissemination rides the aggregation tree — seals merge
            # upward as partial aggregates, one O(1) quorum certificate
            # broadcasts down, every node finalizes with ONE pairing.
            from go_ibft_tpu.crypto.quorum_cert import (
                BLSCertifier,
                BLSKeyRegistry,
            )
            from go_ibft_tpu.net import AggregationTreeGossip

            registry = BLSKeyRegistry()
            for k, bk in zip(keys, bls_keys):
                registry.register_key(k.address, bk)
            bls_src = registry
            certifier = BLSCertifier(validators, registry)
            hub = AggregationTreeGossip(certifier, fan_in=2)
        else:
            pubkeys = {
                k.address: bk.pubkey for k, bk in zip(keys, bls_keys)
            }
            bls_src = ECDSABackend.static_validators(pubkeys)

    # 2. One engine per validator, all wired to one loopback "network"
    # (or the aggregation tree in --aggregate mode).
    transport = LoopbackTransport() if hub is None else None
    engines = []
    for i, key in enumerate(keys):
        build = lambda view: b"example block %d" % view.height  # noqa: E731
        if use_bls:
            backend = HybridBLSBackend(
                key, bls_keys[i], validators, bls_src, build_proposal_fn=build
            )
        else:
            # The embedder's block builder: anything bytes. A real chain
            # would assemble transactions here (reference Backend.BuildProposal).
            backend = ECDSABackend(
                key,
                validators,
                build_proposal_fn=build,
                commit_next_set=commit_next_set,
            )
        batch_verifier = None
        if use_mesh:
            # Production scale-out posture: the adaptive router with the
            # sharded mesh rung on top — tiny drains stay on host, large
            # ones on one device, drains past the mesh cutover shard
            # lane-parallel across every visible device (forced host
            # devices work too: XLA_FLAGS=--xla_force_host_platform_
            # device_count=8).  Degrades transparently to the plain
            # device ladder on a 1-device host.  The engine certify
            # drains AND (in --chain mode) the overlap/sync drains all
            # route through the same ladder.
            from go_ibft_tpu.verify import (
                AdaptiveBatchVerifier,
                MeshBatchVerifier,
            )

            mesh_verifier = MeshBatchVerifier(validators)
            batch_verifier = AdaptiveBatchVerifier(
                validators,
                mesh=mesh_verifier if mesh_verifier.sharded else None,
            )
            batch_verifier.warmup()
        elif use_device:
            from go_ibft_tpu.verify import DeviceBatchVerifier

            batch_verifier = DeviceBatchVerifier(validators)
            batch_verifier.warmup()  # node startup: never compile mid-round
            if use_bls:
                # An explicit batch_verifier overrides the backend's seal
                # path, and BLS seals are 192 bytes — the ECDSA device
                # verifier would reject every one.  Compose instead: device
                # ECDSA for sender envelopes, BLS aggregate for seals.
                from go_ibft_tpu.crypto.bls_backend import HybridBatchVerifier
                from go_ibft_tpu.verify.bls import BLSAggregateVerifier

                batch_verifier = HybridBatchVerifier(
                    batch_verifier, BLSAggregateVerifier(bls_src)
                )
        speculator = None
        if use_speculate:
            # Commit-critical-path posture (ISSUE 9): COMMIT seals
            # arriving ahead of their phase verify off the event loop
            # through the engine's own verifier, and the commit drain
            # early-exits at quorum (on by default), deferring the
            # remainder to the same worker.
            from go_ibft_tpu.verify import HostBatchVerifier as _HBV
            from go_ibft_tpu.verify import SpeculativeVerifier

            speculator = SpeculativeVerifier(
                batch_verifier
                if batch_verifier is not None
                else _HBV(validators)
            )
        engine = IBFT(
            StdoutLogger(),
            backend,
            transport,
            batch_verifier=batch_verifier,
            cert_verifier=certifier,
            speculator=speculator,
        )
        engine.set_base_round_timeout(10.0)
        if hub is not None:
            engine.transport = hub.register(
                key.address, engine.add_message, engine.add_quorum_certificate
            )
        else:
            transport.register(engine.add_message)
        engines.append(engine)
    return engines, certifier, hub


async def main_async(
    n: int,
    heights: int,
    use_device: bool,
    use_bls: bool = False,
    use_mesh: bool = False,
    use_aggregate: bool = False,
    use_speculate: bool = False,
) -> None:
    engines, _certifier, hub = build_cluster(
        n, use_device, use_bls, use_mesh, use_aggregate, use_speculate
    )
    if hub is not None:
        hub.start()
    try:
        for h in range(1, heights + 1):
            # Every validator runs the height concurrently; run_sequence
            # returns once the proposal is finalized on that node.
            await asyncio.gather(*(e.run_sequence(h) for e in engines))
    finally:
        if hub is not None:
            await hub.stop()
        for e in engines:
            e.messages.close()
            if e.speculator is not None:
                e.speculator.stop()

    _print_chains(engines)
    if use_speculate:
        stats = engines[0].speculator.stats()
        print(
            f"speculation: {stats['speculated_lanes']} lanes off-path, "
            f"{stats['cache_hits']} drain cache hits"
        )
    if hub is not None:
        stats = hub.stats()
        print(
            f"aggregation tree: {stats['certs_built']} certs, worst node "
            f"sent {max(stats['commit_bytes_per_node'])} commit bytes"
        )


async def main_chain(
    n: int,
    heights: int,
    use_device: bool,
    use_bls: bool = False,
    use_mesh: bool = False,
    use_aggregate: bool = False,
    use_speculate: bool = False,
    telemetry_port: Optional[int] = None,
) -> None:
    """The continuous-node mode: one ChainRunner per validator.

    Unlike :func:`main_async`'s per-height ``asyncio.gather`` barrier,
    each node owns ONE persistent runner task that drives heights
    back-to-back: finalized blocks and mid-round locks are WAL-persisted
    (``wal-<i>.jsonl`` in a temp dir — point it at real storage in a
    deployment and call ``runner.recover()`` on restart), a node that
    falls behind rejoins via batched block-sync, and buffered next-height
    traffic is pre-verified while the current height's COMMIT drain is in
    flight.  See docs/CHAIN.md.
    """
    import os
    import tempfile

    from go_ibft_tpu.chain import (
        ChainRunner,
        LoopbackSyncNetwork,
        SyncClient,
        WriteAheadLog,
    )
    from go_ibft_tpu.verify import HostBatchVerifier

    engines, certifier, hub = build_cluster(
        n, use_device, use_bls, use_mesh, use_aggregate, use_speculate
    )
    network = LoopbackSyncNetwork()
    runners = []
    with tempfile.TemporaryDirectory() as tmp:
        for i, engine in enumerate(engines):
            src = engine.backend.get_voting_powers
            runner = ChainRunner(
                engine,
                WriteAheadLog(os.path.join(tmp, f"wal-{i}.jsonl")),
                certifier=certifier,
                sync=SyncClient(
                    engine.backend.id(),
                    network,
                    engine.batch_verifier or HostBatchVerifier(src),
                    src,
                    cert_verifier=certifier,
                ),
            )
            network.register(engine.backend.id(), runner)
            runners.append(runner)
        telemetry = None
        if telemetry_port is not None:
            # The telemetry plane (docs/OBSERVABILITY.md): node 0 serves
            # /metrics (Prometheus text), /healthz (liveness; flips when
            # the runner wedges), and /statusz (height/round, breaker,
            # speculation + ring stats) while the chain runs.
            telemetry = runners[0].start_telemetry(port=telemetry_port)
            print(f"telemetry: {telemetry.url}/metrics /healthz /statusz")
        if hub is not None:
            hub.start()
        try:
            await asyncio.gather(
                *(r.run(until_height=heights) for r in runners)
            )
        finally:
            if telemetry is not None:
                telemetry.stop()
            if hub is not None:
                await hub.stop()
            for engine in engines:
                engine.messages.close()
        for i, runner in enumerate(runners):
            stats = runner.stats()
            print(
                f"validator {i}: height={runner.latest_height()} "
                f"handoff_ms_mean={stats['handoff_ms_mean']:.3f} "
                f"overlapped_lanes={stats['overlapped_lanes']} "
                f"synced={stats['synced_heights']}"
            )
    _print_chains(engines)


def main_serve(
    n: int, heights: int, clients: int, checkpoint_spacing: int = 0
) -> None:
    """Proof-serving mode (``--serve N``): run a chain to finality, then
    serve finality proofs to N synthetic light clients.

    The chain side is ``--chain`` in miniature (one ChainRunner per
    validator, no WAL — the serve layer reads the in-memory chain tail
    through the runner's ``SyncSource`` seam); the read side mounts a
    :class:`~go_ibft_tpu.serve.ProofServer` on runner 0 and hammers it
    from N client threads, each verifying its proof against the trusted
    genesis checkpoint.  Prints proofs/s and the cache hit rates — the
    docs/SERVING.md read-plane story at toy scale.

    ``--checkpoint-spacing S`` (ISSUE 20) additionally seals an epoch
    checkpoint certificate every S heights, serves the skip chain over a
    real HTTP :class:`~go_ibft_tpu.node.proof_api.ProofApiServer`, and
    cold-syncs a :class:`~go_ibft_tpu.lightsync.CheckpointClient`
    against it — printing checkpoint-anchored vs full-walk sync bytes.
    Proposals then carry next-set commitments so the tail proof verifies
    with ``require_commitments`` on (the fabricated-diff defense).
    """
    import threading
    import time

    from go_ibft_tpu.chain import ChainRunner
    from go_ibft_tpu.serve import ProofBuilder, ProofCache, ProofServer

    engines, _certifier, _hub = build_cluster(
        n, use_device=False, commit_next_set=checkpoint_spacing > 0
    )
    runners = [ChainRunner(engine, overlap=False) for engine in engines]

    async def drive() -> None:
        await asyncio.gather(*(r.run(until_height=heights) for r in runners))

    try:
        asyncio.run(drive())
    finally:
        for engine in engines:
            engine.messages.close()
    _print_chains(engines)

    source = runners[0]  # ChainRunner IS a SyncSource
    server = ProofServer(
        ProofBuilder(source, source.validators_for_height),
        ProofCache(chunk_heights=2),
    )
    verified = []
    t0 = time.perf_counter()

    def client(i: int) -> None:
        # staggered checkpoints: overlapping ranges share cached chunks
        checkpoint = i % max(1, heights - 1)
        proof = server.get_proof(checkpoint)
        # the trust anchor is the CHECKPOINT's next-height set — a client
        # must never verify against a set its checkpoint does not vouch
        # for (matters the moment the validator set rotates)
        server.verify_proof(
            proof, source.validators_for_height(checkpoint + 1)
        )
        verified.append(proof.target)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    stats = server.stats()
    print(
        f"served+verified {len(verified)} proofs in {elapsed * 1e3:.1f} ms "
        f"({len(verified) / elapsed:.1f} proofs/s)"
    )
    print(
        f"proof cache: {stats['cache']['hits']} hits / "
        f"{stats['cache']['misses']} misses "
        f"(hit rate {stats['cache']['hit_rate']}), "
        f"sig-verdict cache hit rate "
        f"{stats['verify']['sig_cache']['hit_rate']}"
    )

    if checkpoint_spacing <= 0:
        return

    # -- ISSUE 20: checkpoint-anchored cold sync over real HTTP ----------
    from go_ibft_tpu.crypto import bls as hbls
    from go_ibft_tpu.crypto.backend import proposal_hash_of
    from go_ibft_tpu.crypto.quorum_cert import BLSKeyRegistry
    from go_ibft_tpu.lightsync import CheckpointClient, Checkpointer
    from go_ibft_tpu.node.proof_api import ProofApiServer

    # Epoch certificates are BLS-sealed; register PoP-gated keys for the
    # same validator identities (rogue-key defense lives in the registry).
    addrs = sorted(source.validators_for_height(1))
    bls_signers = {
        a: hbls.BLSPrivateKey.from_seed(b"example-ckpt-bls-%d" % i)
        for i, a in enumerate(addrs)
    }
    registry = BLSKeyRegistry()
    for a, k in bls_signers.items():
        registry.register_key(a, k)
    checkpointer = Checkpointer(
        checkpoint_spacing, source.validators_for_height, signers=bls_signers
    )
    for block in source.get_blocks(1, source.latest_height()):
        checkpointer.on_finalize(
            block.height, proposal_hash_of(block.proposal)
        )

    api = ProofApiServer(
        server,
        source.latest_height,
        checkpoints_fn=checkpointer.wire_payload,
    )
    api.start()
    try:
        light = CheckpointClient(api.url, registry)
        genesis_powers = source.validators_for_height(1)
        report = light.cold_sync(genesis_powers)
        # Full-walk baseline over the SAME wire: one finality proof from
        # the genesis trust anchor, every height a diff hop.
        _, full_walk_bytes = light.fetch_proof(0, report.target)
        print(
            f"checkpoint sync (spacing {checkpoint_spacing}): anchored at "
            f"height {report.anchor_height} (epoch {report.anchor_epoch}), "
            f"skipped {report.heights_skipped} heights, "
            f"{report.pairing_dispatches} batched pairing dispatch(es)"
        )
        print(
            f"  checkpoint-anchored: {report.total_bytes} bytes "
            f"(certs {report.checkpoint_bytes} + bridges "
            f"{report.bridge_bytes} + tail {report.tail_bytes})"
        )
        print(
            f"  full walk from genesis: {full_walk_bytes} bytes "
            f"({full_walk_bytes / max(1, report.total_bytes):.1f}x)"
        )
    finally:
        api.stop()


def main_tenants(n: int, heights: int, tenants: int) -> None:
    """Multi-tenant mode (``--tenants N``): N independent chains — their
    own validator sets, proposals and WALs — share ONE process-wide
    :class:`~go_ibft_tpu.sched.TenantScheduler`, so every chain's verify
    drains coalesce into shared batched dispatches instead of issuing N
    small ones (docs/TENANCY.md).  Each chain runs in its own event-loop
    thread (the many-embedders-one-process posture); per-tenant drain
    latency SLOs print at the end from ``scheduler.stats()``.
    """
    import threading

    from go_ibft_tpu.chain import ChainRunner
    from go_ibft_tpu.core import BatchingIngress
    from go_ibft_tpu.sched import TenantScheduler

    scheduler = TenantScheduler(window_s=0.001, route="auto")

    async def one_chain(chain: int) -> list:
        keys = [
            PrivateKey.from_seed(b"tenant-%d-validator-%d" % (chain, i))
            for i in range(n)
        ]
        validators = ECDSABackend.static_validators({k.address: 1 for k in keys})
        nodes = []

        class _T:
            def multicast(self, message):
                for ingress in nodes:
                    ingress.submit(message)

        runners = []
        for i, key in enumerate(keys):
            handle = scheduler.register(
                f"chain-{chain}/node-{i}", validators, chain_id=f"chain-{chain}"
            )
            build = (
                lambda view, c=chain: b"tenant %d block %d" % (c, view.height)
            )  # noqa: E731
            engine = IBFT(
                StdoutLogger() if chain == 0 and i == 0 else _QuietLogger(),
                ECDSABackend(key, validators, build_proposal_fn=build),
                _T(),
                batch_verifier=handle,
            )
            engine.set_base_round_timeout(10.0)
            nodes.append(BatchingIngress(engine.add_messages))
            runners.append(ChainRunner(engine, overlap=False))
        try:
            await asyncio.gather(*(r.run(until_height=heights) for r in runners))
        finally:
            for r, ingress in zip(runners, nodes):
                ingress.close()
                r.engine.messages.close()
        return [b.proposal.raw_proposal for b in runners[0].chain]

    chains: dict = {}

    def chain_thread(chain: int) -> None:
        chains[chain] = asyncio.run(one_chain(chain))

    with scheduler:
        threads = [
            threading.Thread(target=chain_thread, args=(c,))
            for c in range(tenants)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    stats = scheduler.stats()
    for c in sorted(chains):
        blocks = [b.decode() for b in chains[c]]
        p99 = max(
            (
                t["drain_p99_ms"]
                for t in stats["tenants"].values()
                if t["chain"] == f"chain-{c}" and t["drain_p99_ms"] is not None
            ),
            default=None,
        )
        print(f"chain {c}: {blocks} drain_p99_ms={p99}")
    print(
        f"scheduler: {stats['coalesced_requests']} requests coalesced into "
        f"{stats['dispatches']} dispatches "
        f"(ratio {stats['coalesce_ratio']}), "
        f"{stats['flush_faults']} flush faults"
    )


class _QuietLogger:
    def info(self, msg, *args):
        pass

    debug = error = info


def _print_chains(engines) -> None:
    from go_ibft_tpu.lightsync import strip_next_set

    for i, e in enumerate(engines):
        # Commitment-carrying proposals end in a 52-byte binary suffix
        # (lightsync.commitment) — strip it for the human-readable chain.
        chain = [
            strip_next_set(p.raw_proposal).decode()
            for p, _seals in e.backend.inserted
        ]
        _p, last_seals = e.backend.inserted[-1]
        if e.finalized_certificate is not None:
            evidence = (
                f"one {len(e.finalized_certificate.encode())}-byte "
                "aggregate certificate"
            )
        else:
            evidence = f"{len(last_seals)} seals"
        print(f"validator {i}: chain={chain} (last block carries {evidence})")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--heights", type=int, default=3)
    ap.add_argument(
        "--device",
        action="store_true",
        help="verify PREPARE/COMMIT phases through the fused device kernels",
    )
    ap.add_argument(
        "--mesh",
        action="store_true",
        help="shard large verify drains lane-parallel across the device "
        "mesh (AdaptiveBatchVerifier + MeshBatchVerifier; degrades to "
        "--device behavior on a 1-device host)",
    )
    ap.add_argument(
        "--bls",
        action="store_true",
        help="BLS12-381 committed seals (one pairing certifies a quorum)",
    )
    ap.add_argument(
        "--aggregate",
        action="store_true",
        help="the full aggregate-COMMIT mode (implies --bls): PoP-gated "
        "key registry, aggregation-tree COMMIT dissemination, engines "
        "finalize from one O(1) quorum certificate, and (--chain) the "
        "WAL/sync carry certificates instead of per-validator seals",
    )
    ap.add_argument(
        "--speculate",
        action="store_true",
        help="speculative cross-phase verification: COMMIT seals verify "
        "off the event loop as they arrive (before their phase opens) "
        "and the commit drain early-exits at quorum (ISSUE 9)",
    )
    ap.add_argument(
        "--chain",
        action="store_true",
        help="drive heights through ChainRunners (persistent per-node "
        "height loops, WAL + block-sync) instead of the per-height "
        "gather barrier",
    )
    ap.add_argument(
        "--tenants",
        type=int,
        default=0,
        metavar="N",
        help="multi-tenant mode: run N independent chains (each --nodes "
        "validators) whose verify drains coalesce through ONE process-"
        "wide TenantScheduler (docs/TENANCY.md); prints per-tenant drain "
        "p99 and the coalesce ratio",
    )
    ap.add_argument(
        "--telemetry",
        type=int,
        default=None,
        metavar="PORT",
        help="(--chain mode) mount the telemetry endpoints on node 0: "
        "/metrics (Prometheus text), /healthz (liveness), /statusz "
        "(operator status JSON); 0 binds an ephemeral port "
        "(docs/OBSERVABILITY.md)",
    )
    ap.add_argument(
        "--serve",
        type=int,
        default=0,
        metavar="N",
        help="proof-serving mode: finalize --heights blocks, then serve "
        "finality proofs to N synthetic light clients through a "
        "ProofServer mounted on the chain (docs/SERVING.md); prints "
        "proofs/s and cache hit rates",
    )
    ap.add_argument(
        "--checkpoint-spacing",
        type=int,
        default=0,
        metavar="S",
        help="(--serve mode) seal an epoch checkpoint certificate every S "
        "heights and cold-sync a CheckpointClient over real HTTP; prints "
        "checkpoint-anchored vs full-walk sync bytes (docs/SERVING.md)",
    )
    args = ap.parse_args()
    if args.serve:
        main_serve(
            args.nodes, args.heights, args.serve, args.checkpoint_spacing
        )
    elif args.tenants:
        main_tenants(args.nodes, args.heights, args.tenants)
    else:
        if args.chain:
            asyncio.run(
                main_chain(
                    args.nodes,
                    args.heights,
                    args.device,
                    args.bls,
                    args.mesh,
                    args.aggregate,
                    args.speculate,
                    telemetry_port=args.telemetry,
                )
            )
        else:
            asyncio.run(
                main_async(
                    args.nodes,
                    args.heights,
                    args.device,
                    args.bls,
                    args.mesh,
                    args.aggregate,
                    args.speculate,
                )
            )
