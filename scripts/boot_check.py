"""Second-boot cache proof over two AOT manifests (CI fast tier).

``make boot-check`` runs ``scripts/warm_kernels.py --aot-only`` twice
against one fresh temp cache dir and hands both manifests here:

* run 1 (cold dir) pays the real compile and must RECORD it — a manifest
  with zero events means the "cold" leg found a pre-warmed cache and the
  comparison would prove nothing;
* run 2 (same dir, ``--no-skip``) must hit the persistent cache, so its
  measured per-family wall must collapse.  The gate is a RATIO (default:
  second run < 50% of the first), not an absolute threshold — it scales
  with machine speed instead of flaking on slow CI runners (the measured
  regime on the digest family is ~10x: 0.43 s compile vs 0.04 s load).

Both manifests must carry the same, non-stale fingerprint (jax version /
backend / device count) — a mismatch means the two runs didn't exercise
the same cache key and the ratio is meaningless.

Exit code: 0 = cache proven, 2 = check failed, 1 = unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys


def check(m1: dict, m2: dict, *, ratio: float) -> list:
    """Return a list of human-readable failures (empty = pass)."""
    failures = []
    if m1.get("fingerprint") != m2.get("fingerprint"):
        failures.append(
            f"fingerprint mismatch: {m1.get('fingerprint')} vs "
            f"{m2.get('fingerprint')} — the runs keyed different caches"
        )
    p1, p2 = m1.get("programs", {}), m2.get("programs", {})
    if not p1:
        failures.append("first manifest recorded no programs")
    for family, acc1 in sorted(p1.items()):
        acc2 = p2.get(family)
        if acc2 is None:
            failures.append(f"{family}: missing from second manifest")
            continue
        cold_ms = float(acc1.get("compile_ms", 0.0))
        warm_ms = float(acc2.get("compile_ms", 0.0))
        if cold_ms <= 0.0:
            failures.append(
                f"{family}: first run measured no compile wall — the "
                "'cold' leg never compiled (pre-warmed cache dir?)"
            )
            continue
        if warm_ms >= cold_ms * ratio:
            failures.append(
                f"{family}: second boot paid {warm_ms:.1f} ms vs "
                f"{cold_ms:.1f} ms cold (>= {ratio:.0%}) — the persistent "
                "cache did not absorb the compile"
            )
        else:
            print(
                f"boot-check {family}: cold {cold_ms:.1f} ms -> warm "
                f"{warm_ms:.1f} ms ({warm_ms / cold_ms:.1%})"
            )
    return failures


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="scripts/boot_check.py")
    p.add_argument("cold_manifest", help="manifest from the cold run")
    p.add_argument("warm_manifest", help="manifest from the second run")
    p.add_argument(
        "--ratio",
        type=float,
        default=0.5,
        help="second run must cost less than this fraction of the first "
        "per family (default 0.5; measured regime is ~0.1)",
    )
    args = p.parse_args(argv)
    try:
        with open(args.cold_manifest) as fh:
            m1 = json.load(fh)
        with open(args.warm_manifest) as fh:
            m2 = json.load(fh)
    except (OSError, ValueError) as err:
        print(f"boot-check: unreadable manifest: {err}", file=sys.stderr)
        return 1
    failures = check(m1, m2, ratio=args.ratio)
    for failure in failures:
        print(f"boot-check FAIL: {failure}", file=sys.stderr)
    if not failures:
        print("boot-check OK: second boot loaded every family from cache")
    return 2 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
