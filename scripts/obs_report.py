#!/usr/bin/env python
"""Regression-gate report: fresh bench evidence vs the best prior rounds.

Usage::

    python scripts/obs_report.py [--evidence bench_evidence.jsonl]
                                 [--repo .] [--fail-on fail|warn|never]

Parses the fresh evidence file (``bench.py``'s per-config JSONL — or any
``BENCH_r*.json`` driver artifact), determines its backend
(``tpu`` vs ``cpu-fallback``), compares each config against the best
prior ``BENCH_r*.json`` value recorded on the SAME backend, and prints a
pass/warn/fail table (``go_ibft_tpu.obs.gates``).  Exit code: 0 unless a
row at or above ``--fail-on`` severity exists (default ``fail``); 2 when
the evidence file is missing/unreadable.

``make obs-report`` runs this with defaults.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from go_ibft_tpu.obs import gates  # noqa: E402


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--evidence",
        default="bench_evidence.jsonl",
        help="fresh evidence file (bench.py JSONL or BENCH_r*.json wrapper)",
    )
    parser.add_argument(
        "--repo", default=".", help="repo root holding prior BENCH_r*.json"
    )
    parser.add_argument(
        "--fail-on",
        choices=("fail", "warn", "never"),
        default="fail",
        help="lowest severity that makes the exit code nonzero",
    )
    args = parser.parse_args()

    try:
        fresh = gates.parse_artifact(args.evidence)
    except OSError as err:
        print(
            f"obs_report: cannot read {args.evidence!r} ({err}); "
            "run `python bench.py` (or `make bench`) first",
            file=sys.stderr,
        )
        return 2
    if not fresh:
        print(
            f"obs_report: {args.evidence!r} holds no metric lines", file=sys.stderr
        )
        return 2

    # Exclude the fresh file from the prior pool if it IS a BENCH_r*.json.
    backend = gates.artifact_backend(fresh)
    results = gates.gate_evidence(
        fresh,
        args.repo,
        backend=backend,
        exclude=(os.path.basename(args.evidence),),
    )

    print(f"evidence: {args.evidence}  backend: {backend}")
    # "No silent caps": a --trace run whose ring wrapped produced a
    # TRUNCATED flight-recorder window — say so next to the numbers, or a
    # partial timeline reads as a complete one.
    for line in fresh:
        if line.get("metric") == "trace_export":
            dropped = line.get("dropped_records", 0) or 0
            if dropped:
                print(
                    f"WARNING: trace export {line.get('path')!r} dropped "
                    f"{dropped} records (ring wrapped) — the trace window "
                    "is incomplete"
                )
            else:
                print(
                    f"trace export: {line.get('path')!r} "
                    f"({line.get('value')} events, 0 dropped)"
                )
    print(gates.render_table(results))
    statuses = {r.status for r in results}
    # Cost-ledger gates (ISSUE 14): per-config dispatch-count growth and
    # occupancy, from the ledger blocks stamped on evidence lines.
    # Rendered whenever the fresh artifact carries any; graded against
    # the best prior round on the same backend with the tighter ledger
    # thresholds (dispatch counts are near-deterministic per config).
    ledger_results = gates.gate_ledger_evidence(
        fresh,
        args.repo,
        backend=backend,
        exclude=(os.path.basename(args.evidence),),
    )
    if ledger_results:
        print()
        print("cost ledger (per-config dispatches / occupancy):")
        print(gates.render_table(ledger_results))
        statuses |= {r.status for r in ledger_results}
    bad = {"fail"} if args.fail_on == "fail" else {"fail", "warn"}
    if args.fail_on != "never" and statuses & bad:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
