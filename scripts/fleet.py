#!/usr/bin/env python
"""Multi-process fleet smoke: real validator processes, real sockets, SLO-gated.

The CI entry (`make fleet-smoke` / `make fleet-bench`) for the node
layer (ISSUE 19).  Launches N `python -m go_ibft_tpu.node` subprocesses
gossiping IBFT over TCP/gRPC, floods their proof APIs with a concurrent
client fleet plus churn/slowloris adversaries, then grades the run
through the SLO gates:

* missed_heights == 0 — every node finalized every height under flood;
* diverged_chains == 0 — the full-range proof is byte-identical from
  every node (agreement proven over the untrusted-client wire);
* slowloris_uncut == 0 — the header timeout cut every trickling socket;
* proof p99 / consensus finalize p99 latency bounds.

After the drain it reconstructs the cross-process consensus timeline
from the per-node trace exports and prints the critical-path report.
Exit 0 iff every gate held.

    python scripts/fleet.py [--nodes 4] [--heights 3] [--connections 64]
        [--seed 7] [--run-dir DIR] [--slo-out slo.jsonl]
        [--proof-p99-fail-ms N] [--finalize-p99-fail-ms N]
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run(args, run_dir: str) -> int:
    from go_ibft_tpu.obs import gates, timeline
    from go_ibft_tpu.sim.fleet import FleetSpec, run_fleet

    spec = FleetSpec(
        nodes=args.nodes,
        heights=args.heights,
        connections=args.connections,
        churn_clients=args.churn_clients,
        slowloris_clients=args.slowloris_clients,
        seed=args.seed,
        think_s=args.think_s,
        header_timeout_s=args.header_timeout_s,
        min_flood_s=args.min_flood_s,
    )
    print(
        f"fleet: {spec.nodes} validator processes, {spec.connections} "
        f"client connections, {spec.churn_clients} churn + "
        f"{spec.slowloris_clients} slowloris adversaries, seed={spec.seed}"
    )
    result = run_fleet(spec, run_dir)
    print(json.dumps({"fleet": result.summary()}))
    print(result.replay_line)

    failures = []
    # The adversary contract: the server must have cut EVERY slowloris
    # socket it accepted (uncut sockets == capacity bleeding away).
    slow = result.slowloris
    slowloris_uncut = max(0, slow["opened"] - slow["cut_by_server"])
    records = [
        gates.slo_record(
            "missed_heights",
            result.missed_heights,
            context={"nodes": spec.nodes, "heights": spec.heights},
        ),
        gates.slo_record(
            "fleet_diverged_chains",
            result.diverged_chains,
            fail=0.0,
            context={"heads": result.heads},
        ),
        gates.slo_record(
            "fleet_slowloris_uncut",
            slowloris_uncut,
            fail=0.0,
            context=slow,
        ),
    ]
    if result.proof_p99_ms is None:
        failures.append("client fleet recorded no proof latencies")
    else:
        records.append(
            gates.slo_record(
                "fleet_proof_p99_ms",
                result.proof_p99_ms,
                fail=float(args.proof_p99_fail_ms),
                context={"proofs": result.proofs_total},
            )
        )
    if result.finalize_p99_ms is not None:
        records.append(
            gates.slo_record(
                "finalize_p99_ms",
                result.finalize_p99_ms,
                fail=float(args.finalize_p99_fail_ms),
            )
        )
    elif result.timeline_heights == 0:
        failures.append("cross-process timeline reconstructed 0 heights")
    gates.append_slo_records(args.slo_out, records)
    results = gates.gate_slo_records(records)
    print(gates.render_table(results))
    if any(r.status == "fail" for r in results):
        failures.append("SLO gate failed")

    if result.verified_proofs < spec.nodes and result.missed_heights == 0:
        failures.append(
            f"spot-verified {result.verified_proofs}/{spec.nodes} proofs"
        )
    for i, report in enumerate(result.reports):
        if not report:
            failures.append(f"node {i} emitted no drain report")

    # The cross-process critical-path report, from N separate processes'
    # trace files on one aligned clock.
    if result.trace_paths:
        files = [timeline.load_trace_file(p) for p in result.trace_paths]
        print()
        print(timeline.render_report(timeline.reconstruct(timeline.merge_events(files))))

    if failures:
        for failure in failures:
            print(f"FAIL: {failure}", file=sys.stderr)
        return 1
    print(
        f"\nfleet OK: {spec.nodes} processes finalized {result.heads} under "
        f"{result.peak_connections} concurrent connections, "
        f"{result.proofs_total} proofs served "
        f"({result.proofs_s:.1f}/s, p99 {result.proof_p99_ms}ms), "
        f"{result.timeline_heights} heights on the cross-process timeline"
    )
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--heights", type=int, default=3)
    parser.add_argument("--connections", type=int, default=64)
    parser.add_argument("--churn-clients", type=int, default=2)
    parser.add_argument("--slowloris-clients", type=int, default=2)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--think-s", type=float, default=0.5)
    parser.add_argument("--header-timeout-s", type=float, default=1.0)
    parser.add_argument("--min-flood-s", type=float, default=2.0)
    parser.add_argument("--proof-p99-fail-ms", type=float, default=30_000.0)
    parser.add_argument("--finalize-p99-fail-ms", type=float, default=60_000.0)
    parser.add_argument(
        "--run-dir", default=None, help="keep configs/logs/traces here"
    )
    parser.add_argument(
        "--slo-out",
        default=os.environ.get("GO_IBFT_SLO_PATH"),
        help="append SLO records here (JSONL; default $GO_IBFT_SLO_PATH)",
    )
    args = parser.parse_args()
    if args.run_dir:
        os.makedirs(args.run_dir, exist_ok=True)
        return _run(args, args.run_dir)
    with tempfile.TemporaryDirectory() as tmp:
        return _run(args, tmp)


if __name__ == "__main__":
    sys.exit(main())
