#!/usr/bin/env python
"""Replay a chaos-soak schedule from its seed.

A failing chaos test prints one artifact line::

    CHAOS-REPLAY seed=N schedule=<digest> config={...}

Re-run the exact scenario with::

    python scripts/chaos_replay.py --seed N [--heights 5] [--nodes 6]

or paste the WHOLE artifact line (both replay planes share the format)::

    python scripts/chaos_replay.py --line 'CHAOS-REPLAY seed=... config=...'

``--seed`` rebuilds the injector-based ECDSA soak (tests/test_chaos.py's
shape).  ``--line`` inspects the config: a lock-step cluster line (one
whose config carries ``chaos``/``adversary`` sections, as emitted by
``go_ibft_tpu.sim.adversary.cluster_replay_line``) rebuilds the ChaosMask
AND the AdversaryMix, re-runs the exact ClusterSim scenario — attackers
included — recomputes the combined schedule digest over the tick/height
horizon recorded in the line, and reports the invariant verdict; a bare
ChaosMask line replays the mask-only cluster.  A FLEET line (config
carries a ``fleet`` section, as emitted by
``go_ibft_tpu.chaos.fleet_replay_line``) replays the seeded adversarial
CLIENT plan — churn + slowloris — against a fresh in-process proof API
and re-asserts the header-timeout defense plus the schedule digest.
Exit code 0 = clean replay; 1 = the failure reproduced (missed heights,
an invariant violation, or an uncut slowloris socket); 2 = digest
mismatch (you did not replay the same schedule).
"""

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from go_ibft_tpu.chaos import (  # noqa: E402
    ChaoticDeliver,
    FaultConfig,
    FaultInjector,
)
from go_ibft_tpu.core import IBFT, BatchingIngress  # noqa: E402
from go_ibft_tpu.crypto import PrivateKey  # noqa: E402
from go_ibft_tpu.crypto.backend import ECDSABackend  # noqa: E402
from go_ibft_tpu.utils import metrics  # noqa: E402
from go_ibft_tpu.verify import (  # noqa: E402
    HostBatchVerifier,
    ResilientBatchVerifier,
)

# Default config mirrors tests/test_chaos.py::_SOAK_CFG — override with
# --config to replay a non-default schedule from an artifact line.
DEFAULT_CONFIG = dict(
    drop_rate=0.03,
    delay_rate=0.3,
    max_delay_s=0.01,
    reorder_rate=0.05,
    duplicate_rate=0.05,
    corrupt_rate=0.02,
)


class _Log:
    def info(self, *a):
        pass

    debug = info

    def error(self, msg, *a):
        print(f"ERROR: {msg} {a}", file=sys.stderr)


async def replay(seed: int, heights: int, n_nodes: int, config: FaultConfig) -> int:
    injector = FaultInjector(seed, config)
    print(injector.replay_line(), flush=True)

    keys = [PrivateKey.from_seed(b"chaos-%d" % i) for i in range(n_nodes)]
    src = ECDSABackend.static_validators({k.address: 1 for k in keys})
    gates = []

    class _T:
        def multicast(self, message):
            for gate in gates:
                gate(message)

    nodes = []
    for i, key in enumerate(keys):
        core = IBFT(
            _Log(),
            ECDSABackend(key, src),
            _T(),
            batch_verifier=ResilientBatchVerifier(
                HostBatchVerifier(src), validators_for_height=src
            ),
        )
        core.set_base_round_timeout(1.0)
        ingress = BatchingIngress(core.add_messages)
        gates.append(ChaoticDeliver(ingress.submit, injector, f"deliver:{i}"))
        nodes.append((core, ingress))

    failed = 0
    try:
        for h in range(1, heights + 1):
            t0 = time.monotonic()
            tasks = [
                asyncio.create_task(core.run_sequence(h)) for core, _ in nodes
            ]
            try:
                await asyncio.wait_for(
                    asyncio.gather(*tasks), timeout=75.0
                )
            except asyncio.TimeoutError:
                pass
            finally:
                for task in tasks:
                    if not task.done():
                        task.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
            finalized = sum(
                1 for core, _ in nodes if len(core.backend.inserted) >= h
            )
            print(
                f"height {h}: {finalized}/{n_nodes} finalized "
                f"in {time.monotonic() - t0:.1f}s",
                flush=True,
            )
            if finalized == 0:
                failed = 1
                break
            donor = next(
                core
                for core, _ in nodes
                if len(core.backend.inserted) >= h
            )
            for core, _ in nodes:  # block-sync stragglers (embedder's job)
                if len(core.backend.inserted) < h:
                    core.backend.inserted.append(donor.backend.inserted[h - 1])
    finally:
        for core, ingress in nodes:
            ingress.close()
            core.messages.close()
        await asyncio.sleep(0.05)

    chaos = metrics.counters_snapshot(("go-ibft", "chaos"))
    print("injected:", {k[-1]: v for k, v in sorted(chaos.items())}, flush=True)
    return failed


async def replay_cluster(
    parsed: dict, *, round_timeout: float, height_timeout: float,
    heights_override: int | None = None,
) -> int:
    """Re-run a lock-step ClusterSim scenario from a parsed CHAOS-REPLAY
    line (ChaosMask + AdversaryMix rebuilt from the config blob)."""
    from go_ibft_tpu.sim import (
        AdversaryMix,
        ChaosMask,
        ClusterSim,
        cluster_replay_line,
    )

    cfg = parsed["config"]
    seed = parsed["seed"]
    combined = "chaos" in cfg or "adversary" in cfg
    if combined:
        chaos_cfg = cfg.get("chaos")
        adv_cfg = cfg.get("adversary")
        ticks = int(cfg["ticks"])
        heights = int(cfg["heights"])
    else:  # bare ChaosMask.replay_line: the config IS the mask config
        chaos_cfg, adv_cfg = cfg, None
        ticks, heights = 0, heights_override or 3
    if heights_override:
        heights = heights_override
    chaos = (
        ChaosMask.from_config(chaos_cfg, seed=seed)
        if chaos_cfg is not None
        else None
    )
    mix = None
    if adv_cfg is not None:
        mix = AdversaryMix(
            int(adv_cfg["n_nodes"]),
            int(adv_cfg["seed"]),
            {int(i): s for i, s in adv_cfg["adversaries"].items()},
            unsafe=bool(adv_cfg.get("unsafe", False)),
        )
    n_nodes = (
        chaos.n_nodes if chaos is not None else mix.n_nodes
    )
    cluster_cfg = cfg.get("cluster") or {}
    sim = ClusterSim(
        n_nodes,
        max_msgs=int(cluster_cfg.get("max_msgs", 8)),
        max_bytes=int(cluster_cfg.get("max_bytes", 1024)),
        round_timeout=float(
            cluster_cfg.get("round_timeout", round_timeout)
        ),
        chaos=chaos,
        adversaries=mix,
        monitor=True,
    )
    result = await sim.run(heights, height_timeout=height_timeout)
    missed = result.missed_heights(sim.honest)
    summary = sim.monitor.summary()
    print(
        f"replayed {n_nodes} nodes x {heights} heights "
        f"({len(sim.honest)} honest) in {result.elapsed_s:.1f}s: "
        f"missed_heights={missed} "
        f"diverged={result.diverged_chains(sim.honest)}",
        flush=True,
    )
    print(f"invariants: {summary}", flush=True)
    for violation in sim.monitor.violations:
        print(f"  {violation}", flush=True)
    if combined:
        replayed = cluster_replay_line(chaos, mix, ticks, heights)
        digest = replayed.split("schedule=")[1].split(" ")[0]
        if digest != parsed["schedule"]:
            print(
                f"DIGEST MISMATCH: line says {parsed['schedule']}, "
                f"replay rebuilt {digest}",
                flush=True,
            )
            return 2
        print(f"schedule digest verified: {digest}", flush=True)
    return 1 if (missed or not summary["ok"]) else 0


def replay_fleet(parsed: dict, *, window_s: float = 3.0) -> int:
    """Replay a fleet CHAOS-REPLAY line: the seeded client plan against
    a fresh in-process proof API (no chain needed — churn hits ``/head``
    and slowloris never finishes a request), then re-verify the schedule
    digest the way ``cluster_replay_line`` replays do."""
    import threading

    from go_ibft_tpu.chaos import (
        ChurningClient,
        SlowlorisClient,
        client_schedule_digest,
    )
    from go_ibft_tpu.node.proof_api import ProofApiServer

    cfg = parsed["config"]["fleet"]
    seed = parsed["seed"]
    churn_n = int(cfg.get("churn_clients", 0))
    slow_n = int(cfg.get("slowloris_clients", 0))
    slow_conns = int(cfg.get("slowloris_conns", 4))

    class _NoProofs:
        def get_proof(self, checkpoint, target=None):
            raise RuntimeError("fleet replay serves no proofs")

    api = ProofApiServer(
        _NoProofs(),
        lambda: 0,
        port=0,
        header_timeout_s=0.5,
    )
    port = api.start()
    stop = threading.Event()
    clients = [
        ChurningClient("127.0.0.1", port, seed=seed, client_id=i)
        for i in range(churn_n)
    ] + [
        SlowlorisClient(
            "127.0.0.1", port, seed=seed, client_id=i, conns=slow_conns
        )
        for i in range(slow_n)
    ]
    threads = [
        threading.Thread(target=c.run, args=(stop,), daemon=True)
        for c in clients
    ]
    try:
        for t in threads:
            t.start()
        time.sleep(window_s)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        api.stop()
    churn = sum(c.stats["churns"] for c in clients[:churn_n])
    opened = sum(c.stats["opened"] for c in clients[churn_n:])
    cut = sum(c.stats["cut_by_server"] for c in clients[churn_n:])
    uncut = max(0, opened - cut)
    print(
        f"replayed {churn_n} churn + {slow_n} slowloris clients for "
        f"{window_s:.1f}s: churns={churn} slowloris opened={opened} "
        f"cut_by_server={cut}",
        flush=True,
    )
    digest = client_schedule_digest(seed, churn_n, slow_n)
    if digest != parsed["schedule"]:
        print(
            f"DIGEST MISMATCH: line says {parsed['schedule']}, "
            f"replay rebuilt {digest}",
            flush=True,
        )
        return 2
    print(f"schedule digest verified: {digest}", flush=True)
    if uncut:
        print(f"FAIL: {uncut} slowloris socket(s) never cut", flush=True)
    return 1 if uncut else 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument(
        "--line",
        type=str,
        default=None,
        help="a full CHAOS-REPLAY artifact line; cluster-format lines "
        "(chaos/adversary config sections) re-run the lock-step "
        "ClusterSim scenario, adversaries included",
    )
    parser.add_argument("--round-timeout", type=float, default=2.0)
    parser.add_argument("--height-timeout", type=float, default=60.0)
    parser.add_argument("--heights", type=int, default=None)
    parser.add_argument("--nodes", type=int, default=6)
    parser.add_argument(
        "--config",
        type=str,
        default=None,
        help="JSON FaultConfig overrides (copy off the CHAOS-REPLAY line)",
    )
    parser.add_argument(
        "--trace",
        metavar="OUT_JSON",
        default=None,
        help="record the soak on the flight recorder and export a "
        "Chrome/Perfetto trace (per-node tracks + chaos.* injection "
        "instants) at exit",
    )
    args = parser.parse_args()
    if args.line is not None:
        from go_ibft_tpu.sim import parse_replay_line

        parsed = parse_replay_line(args.line)
        cfg = parsed["config"]
        if "fleet" in cfg:
            return replay_fleet(parsed)
        if "chaos" in cfg or "adversary" in cfg or "n_nodes" in cfg:
            return asyncio.run(
                replay_cluster(
                    parsed,
                    round_timeout=args.round_timeout,
                    height_timeout=args.height_timeout,
                    heights_override=args.heights,
                )
            )
        # Injector-format line: config fields ARE FaultConfig overrides.
        args.seed = parsed["seed"]
        args.config = json.dumps(
            {k: v for k, v in cfg.items() if k != "seed"}
        )
    if args.seed is None:
        parser.error("--seed or --line is required")
    overrides = json.loads(args.config) if args.config else {}
    config = FaultConfig(**{**DEFAULT_CONFIG, **overrides})
    if args.trace:
        from go_ibft_tpu.obs import trace as obs_trace

        obs_trace.enable()
    try:
        return asyncio.run(
            replay(args.seed, args.heights or 5, args.nodes, config)
        )
    finally:
        if args.trace:
            from go_ibft_tpu.obs.export import write_chrome_trace

            n = write_chrome_trace(args.trace)
            print(f"trace: {args.trace} ({n} events)", flush=True)


if __name__ == "__main__":
    sys.exit(main())
