#!/usr/bin/env python
"""Replay a chaos-soak schedule from its seed.

A failing chaos test prints one artifact line::

    CHAOS-REPLAY seed=N schedule=<digest> config={...}

Re-run the exact scenario with::

    python scripts/chaos_replay.py --seed N [--heights 5] [--nodes 6]

The injector is rebuilt from the seed (and optionally a config JSON copied
off the artifact line), the soak cluster re-runs the same deterministic
fault schedule, and the script prints per-height progress plus the final
schedule digest so you can confirm you replayed the right run.  Exit code
0 = every height finalized; 1 = the failure reproduced.
"""

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from go_ibft_tpu.chaos import (  # noqa: E402
    ChaoticDeliver,
    FaultConfig,
    FaultInjector,
)
from go_ibft_tpu.core import IBFT, BatchingIngress  # noqa: E402
from go_ibft_tpu.crypto import PrivateKey  # noqa: E402
from go_ibft_tpu.crypto.backend import ECDSABackend  # noqa: E402
from go_ibft_tpu.utils import metrics  # noqa: E402
from go_ibft_tpu.verify import (  # noqa: E402
    HostBatchVerifier,
    ResilientBatchVerifier,
)

# Default config mirrors tests/test_chaos.py::_SOAK_CFG — override with
# --config to replay a non-default schedule from an artifact line.
DEFAULT_CONFIG = dict(
    drop_rate=0.03,
    delay_rate=0.3,
    max_delay_s=0.01,
    reorder_rate=0.05,
    duplicate_rate=0.05,
    corrupt_rate=0.02,
)


class _Log:
    def info(self, *a):
        pass

    debug = info

    def error(self, msg, *a):
        print(f"ERROR: {msg} {a}", file=sys.stderr)


async def replay(seed: int, heights: int, n_nodes: int, config: FaultConfig) -> int:
    injector = FaultInjector(seed, config)
    print(injector.replay_line(), flush=True)

    keys = [PrivateKey.from_seed(b"chaos-%d" % i) for i in range(n_nodes)]
    src = ECDSABackend.static_validators({k.address: 1 for k in keys})
    gates = []

    class _T:
        def multicast(self, message):
            for gate in gates:
                gate(message)

    nodes = []
    for i, key in enumerate(keys):
        core = IBFT(
            _Log(),
            ECDSABackend(key, src),
            _T(),
            batch_verifier=ResilientBatchVerifier(
                HostBatchVerifier(src), validators_for_height=src
            ),
        )
        core.set_base_round_timeout(1.0)
        ingress = BatchingIngress(core.add_messages)
        gates.append(ChaoticDeliver(ingress.submit, injector, f"deliver:{i}"))
        nodes.append((core, ingress))

    failed = 0
    try:
        for h in range(1, heights + 1):
            t0 = time.monotonic()
            tasks = [
                asyncio.create_task(core.run_sequence(h)) for core, _ in nodes
            ]
            try:
                await asyncio.wait_for(
                    asyncio.gather(*tasks), timeout=75.0
                )
            except asyncio.TimeoutError:
                pass
            finally:
                for task in tasks:
                    if not task.done():
                        task.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
            finalized = sum(
                1 for core, _ in nodes if len(core.backend.inserted) >= h
            )
            print(
                f"height {h}: {finalized}/{n_nodes} finalized "
                f"in {time.monotonic() - t0:.1f}s",
                flush=True,
            )
            if finalized == 0:
                failed = 1
                break
            donor = next(
                core
                for core, _ in nodes
                if len(core.backend.inserted) >= h
            )
            for core, _ in nodes:  # block-sync stragglers (embedder's job)
                if len(core.backend.inserted) < h:
                    core.backend.inserted.append(donor.backend.inserted[h - 1])
    finally:
        for core, ingress in nodes:
            ingress.close()
            core.messages.close()
        await asyncio.sleep(0.05)

    chaos = metrics.counters_snapshot(("go-ibft", "chaos"))
    print("injected:", {k[-1]: v for k, v in sorted(chaos.items())}, flush=True)
    return failed


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, required=True)
    parser.add_argument("--heights", type=int, default=5)
    parser.add_argument("--nodes", type=int, default=6)
    parser.add_argument(
        "--config",
        type=str,
        default=None,
        help="JSON FaultConfig overrides (copy off the CHAOS-REPLAY line)",
    )
    parser.add_argument(
        "--trace",
        metavar="OUT_JSON",
        default=None,
        help="record the soak on the flight recorder and export a "
        "Chrome/Perfetto trace (per-node tracks + chaos.* injection "
        "instants) at exit",
    )
    args = parser.parse_args()
    overrides = json.loads(args.config) if args.config else {}
    config = FaultConfig(**{**DEFAULT_CONFIG, **overrides})
    if args.trace:
        from go_ibft_tpu.obs import trace as obs_trace

        obs_trace.enable()
    try:
        return asyncio.run(replay(args.seed, args.heights, args.nodes, config))
    finally:
        if args.trace:
            from go_ibft_tpu.obs.export import write_chrome_trace

            n = write_chrome_trace(args.trace)
            print(f"trace: {args.trace} ({n} events)", flush=True)


if __name__ == "__main__":
    sys.exit(main())
