"""A/B the double-scalar ladder's 16-way table select on the active backend.

Variant A (shipped): one-hot einsum gather per scan step.
Variant B: branchless 4-level select tree (pure where ops, no dot_general).
Variant C: einsum with the one-hot built once for all 64 windows outside
the scan (trades VMEM for per-step one-hot construction).

Prints one JSON line per variant so the ladder's select strategy is chosen
from device data, not guesses.
"""

import json
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

from go_ibft_tpu.utils.jaxcache import enable_persistent_cache

enable_persistent_cache()

from go_ibft_tpu.ops import fields, secp256k1 as sec
from go_ibft_tpu.ops.secp256k1 import (
    _G_TAB_X,
    _G_TAB_Y,
    _NWIN,
    _L,
    JacobianPoint,
    _scalar_nibbles_msb,
    _sel_pt,
    point_add,
    point_add_mixed,
    point_double,
    point_infinity,
)

FIELD = sec.FIELD
ORDER = sec.ORDER


def _tree_select(sel, table):
    """(16, ..., L) table, integer sel in [0,16): 4-level where tree."""
    b0 = (sel & 1).astype(bool)
    b1 = (sel & 2).astype(bool)
    b2 = (sel & 4).astype(bool)
    b3 = (sel & 8).astype(bool)
    t = [
        fields.select(b0, table[i + 1], table[i]) for i in range(0, 16, 2)
    ]
    t = [fields.select(b1, t[i + 1], t[i]) for i in range(0, 8, 2)]
    t = [fields.select(b2, t[i + 1], t[i]) for i in range(0, 4, 2)]
    return fields.select(b3, t[1], t[0])


def _ladder(k1, k2, qx, qy, select_fn):
    one = jnp.asarray(FIELD.const(1))
    batch = jnp.broadcast_shapes(k1.shape[:-1], k2.shape[:-1], qx.shape[:-1])
    qx = jnp.broadcast_to(qx, batch + (_L,))
    qy = jnp.broadcast_to(qy, batch + (_L,))
    q_pt = JacobianPoint(qx, qy, jnp.broadcast_to(one, batch + (_L,)))
    q_tab = [point_infinity(batch), q_pt]
    for d in range(2, 16):
        q_tab.append(point_add_mixed(q_tab[-1], qx, qy))
    qtx = jnp.stack([t.x for t in q_tab])
    qty = jnp.stack([t.y for t in q_tab])
    qtz = jnp.stack([t.z for t in q_tab])
    n1 = jnp.broadcast_to(
        _scalar_nibbles_msb(fields.canon(ORDER, k1)), (_NWIN,) + batch
    )
    n2 = jnp.broadcast_to(
        _scalar_nibbles_msb(fields.canon(ORDER, k2)), (_NWIN,) + batch
    )
    g_tab_x = jnp.asarray(_G_TAB_X)
    g_tab_y = jnp.asarray(_G_TAB_Y)

    def body(acc, inp):
        d1, d2 = inp
        acc = point_double(point_double(point_double(point_double(acc))))
        with_g = point_add_mixed(
            acc, select_fn(d1, g_tab_x), select_fn(d1, g_tab_y)
        )
        acc = _sel_pt(d1 == 0, acc, with_g)
        addq = JacobianPoint(
            select_fn(d2, qtx), select_fn(d2, qty), select_fn(d2, qtz)
        )
        acc = point_add(acc, addq)
        return acc, None

    acc, _ = jax.lax.scan(body, point_infinity(batch), (n1, n2))
    return acc


def _einsum_select(sel, table):
    oh = (jnp.arange(16) == sel[..., None]).astype(table.dtype)
    return jnp.einsum("...k,k...l->...l", oh, table)


def med(fn, *args, reps=10):
    """p50 with a real device->host readback each rep (block_until_ready
    alone can be a lazy ack on tunneled backends)."""

    def sync(out):
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(leaf).ravel()[:1]

    sync(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append((time.perf_counter() - t0) * 1e3)
    return round(statistics.median(ts), 3)


def main():
    B = 256
    rng = np.random.default_rng(1)
    k1 = jnp.asarray(
        fields.to_limbs([int(rng.integers(1, 2**63)) for _ in range(B)], _L)
    )
    k2 = jnp.asarray(
        fields.to_limbs([int(rng.integers(1, 2**63)) for _ in range(B)], _L)
    )
    qx = jnp.broadcast_to(jnp.asarray(FIELD.const(sec.GX)), (B, _L))
    qy = jnp.broadcast_to(jnp.asarray(FIELD.const(sec.GY)), (B, _L))

    print(json.dumps({"platform": jax.devices()[0].platform, "lanes": B}), flush=True)

    a = jax.jit(lambda *xs: _ladder(*xs, _einsum_select))
    b = jax.jit(lambda *xs: _ladder(*xs, _tree_select))

    ra = a(k1, k2, qx, qy)
    rb = b(k1, k2, qx, qy)
    agree = all(
        bool(jnp.all(fields.canon(FIELD, x) == fields.canon(FIELD, y)))
        for x, y in zip(ra, rb)
    )
    print(json.dumps({"variants_agree": agree}), flush=True)

    print(json.dumps({"einsum_ms": med(a, k1, k2, qx, qy)}), flush=True)
    print(json.dumps({"tree_ms": med(b, k1, k2, qx, qy)}), flush=True)


if __name__ == "__main__":
    main()
