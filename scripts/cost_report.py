#!/usr/bin/env python
"""Runtime cost report: top programs by device time, waste, compile cost.

The read side of the ISSUE 14 cost ledger.  Sources, in priority order:

* ``--drain`` — run a small in-process host-route drain with the ledger
  enabled and report the live snapshot (the ``make cost-report`` CI
  smoke: proves the whole plane — seams, accumulators, attribution —
  renders end to end without any device compile);
* ``--snapshot cost_ledger.json`` — the snapshot ``bench.py`` dumps at
  exit (the acceptance path: report over a real bench run);
* ``--evidence bench_evidence.jsonl`` — per-config ledger blocks stamped
  on evidence lines (dispatches / occupancy / compiles per config).
* ``--compile-ledger compile_ledger.jsonl`` — the append-only compile
  event log (cold-compile duration table per program + call site),
  printed alongside either of the above when the file exists.

Attribution: dispatch records use the family names of the
``scripts/compile_budget.py`` registry (shape suffixes stripped), so the
report maps recorded dispatches onto the pinned program set and prints
the attributed fraction — ``--check`` fails below ``--min-attribution``
(default 0.95) and when a pinned family with recorded dispatches is
missing from the rendered table.

``make cost-report`` runs ``--drain --check``.
"""

import argparse
import json
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Align with the boot plane's topology (tests/conftest.py, boot/__main__,
# scripts/warm_kernels.py): the device-count flag is part of both the
# persistent-cache key and the AOT sidecar fingerprint, so the `cached?`
# column must read the store under the same posture it was minted with.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

BUDGET_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "compile_budget.json",
)

# Shape suffixes the compile-budget registry appends to family names:
# lane/validator buckets (_8l, _128v, ...) and mesh extents (_dp2 ...).
_SHAPE_SUFFIX = re.compile(r"(_dp\d+|_\d+[lv])$")


def pinned_families(budget_path: str = BUDGET_PATH) -> set:
    """Compile-budget registry keys with shape suffixes stripped — THE
    program key space dispatch records attribute into."""
    try:
        with open(budget_path) as fh:
            snapshot = json.load(fh)
    except (OSError, ValueError):
        return set()
    families = set()
    for key in snapshot:
        if key.startswith("_"):
            continue
        family = key
        while True:
            stripped = _SHAPE_SUFFIX.sub("", family)
            if stripped == family:
                break
            family = stripped
        families.add(family)
    return families


def aot_cached_families() -> set:
    """Families the AOT store holds under THIS process's fingerprint.

    Sourced from the boot store's sidecars (``<cache_dir>/aot/``): a
    family in this set was compiled at BOOT (warm_kernels / boot layer)
    and the next boot loads it from cache; anything else in the compile
    tables was paid at first dispatch, mid-round — the exact cost the
    warm-start plane exists to remove."""
    try:
        from go_ibft_tpu.boot.aot import AOTStore, family_of

        return {family_of(p) for p in AOTStore().cached_programs()}
    except Exception:  # noqa: BLE001 - report must render without jax
        return set()


def _cached_tag(name: str, aot_families: set) -> str:
    return "boot" if name in aot_families else "first-dispatch"


def _table(headers, rows) -> str:
    rows = [tuple(str(c) for c in row) for row in rows]
    all_rows = [tuple(headers)] + rows
    widths = [max(len(r[i]) for r in all_rows) for i in range(len(headers))]
    out = []
    for i, row in enumerate(all_rows):
        out.append("  ".join(c.ljust(widths[j]) for j, c in enumerate(row)))
        if i == 0:
            out.append("-" * len(out[0]))
    return "\n".join(out)


def render_snapshot(snap: dict, *, top: int = 20, families=None) -> str:
    """The per-program report over one ledger snapshot."""
    families = pinned_families() if families is None else families
    rows = snap.get("dispatches", [])
    lines = []

    lines.append(f"== top {min(top, len(rows))} programs by device time ==")
    table_rows = []
    for row in rows[:top]:
        waste = row["padded_lanes"] - row["live_lanes"]
        table_rows.append(
            (
                row["program"],
                row["route"],
                row["dispatches"],
                row["live_lanes"],
                row["padded_lanes"],
                "-" if row["occupancy"] is None else f"{row['occupancy']:.3f}",
                waste,
                f"{row['device_ms']:.1f}",
                "yes" if row["program"] in families else "NO",
            )
        )
    lines.append(
        _table(
            (
                "program",
                "route",
                "dispatches",
                "live",
                "padded",
                "occupancy",
                "waste",
                "device_ms",
                "pinned",
            ),
            table_rows,
        )
    )

    total = sum(r["dispatches"] for r in rows)
    attributed = sum(
        r["dispatches"] for r in rows if r["program"] in families
    )
    fraction = attributed / total if total else None
    lines.append("")
    lines.append(
        "attribution: "
        + (
            f"{attributed}/{total} dispatches "
            f"({fraction:.1%}) map to pinned compile-budget families"
            if total
            else "no dispatches recorded"
        )
    )
    unpinned = sorted(
        {r["program"] for r in rows if r["program"] not in families}
    )
    if unpinned:
        lines.append(f"unpinned programs: {', '.join(unpinned)}")
    if snap.get("overflowed"):
        lines.append(
            f"WARNING: {snap['overflowed']} records landed in the overflow "
            "bucket (program key space exceeded the ledger cap)"
        )

    compiles = snap.get("compiles", {})
    if compiles:
        aot_families = aot_cached_families()
        lines.append("")
        lines.append("== compile cost (per program) ==")
        lines.append(
            _table(
                ("program", "compiles", "compile_ms", "cached?"),
                [
                    (
                        name,
                        acc["count"],
                        f"{acc['ms']:.1f}",
                        _cached_tag(name, aot_families),
                    )
                    for name, acc in sorted(
                        compiles.items(), key=lambda kv: -kv[1]["ms"]
                    )
                ],
            )
        )
    return "\n".join(lines)


def render_compile_ledger(path: str, *, top: int = 30) -> str:
    """Cold-compile duration table from the append-only event log."""
    events = []
    try:
        with open(path) as fh:
            for raw in fh:
                raw = raw.strip()
                if not raw.startswith("{"):
                    continue
                try:
                    event = json.loads(raw)
                except ValueError:
                    continue
                if "program" in event and "ms" in event:
                    events.append(event)
    except OSError:
        return f"(no compile ledger at {path!r})"
    if not events:
        return f"(compile ledger {path!r} holds no events)"
    events.sort(key=lambda e: -e["ms"])
    aot_families = aot_cached_families()
    lines = [
        f"== compile events in {path} — append-only across runs "
        f"({len(events)} total, top {min(top, len(events))} by duration) =="
    ]
    lines.append(
        _table(
            ("program", "ms", "shared", "cached?", "site"),
            [
                (
                    e["program"],
                    f"{e['ms']:.1f}",
                    e.get("shared_span", 1),
                    _cached_tag(e["program"], aot_families),
                    e.get("site", ""),
                )
                for e in events[:top]
            ],
        )
    )
    return "\n".join(lines)


def render_evidence(path: str) -> str:
    """Per-config ledger blocks off an evidence JSONL."""
    from go_ibft_tpu.obs import gates

    try:
        lines_in = gates.parse_artifact(path)
    except OSError as err:
        return f"(cannot read evidence {path!r}: {err})"
    rows = []
    for line in lines_in:
        block = line.get("ledger")
        if not isinstance(block, dict):
            continue
        rows.append(
            (
                line.get("metric"),
                block.get("dispatches"),
                "-"
                if block.get("occupancy") is None
                else f"{block['occupancy']:.3f}",
                block.get("device_ms"),
                block.get("compiles"),
                block.get("compile_ms"),
            )
        )
    if not rows:
        return f"(no ledger blocks in {path!r})"
    out = ["== per-config ledger blocks (evidence deltas) =="]
    out.append(
        _table(
            ("config", "dispatches", "occupancy", "device_ms", "compiles", "compile_ms"),
            rows,
        )
    )
    return "\n".join(out)


def run_drain(compile_log=None) -> dict:
    """A small host-route drain with the ledger on (the CI smoke).

    Exercises two pinned program families without a single XLA compile:
    the coalesced host recover flush (``ecdsa_recover``) and the batched
    host multi-pairing (``bls_multipair_miller``).  Returns the live
    snapshot.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from go_ibft_tpu.crypto import PrivateKey
    from go_ibft_tpu.crypto import bls as hbls
    from go_ibft_tpu.crypto.backend import ECDSABackend, proposal_hash_of
    from go_ibft_tpu.messages.helpers import extract_committed_seal
    from go_ibft_tpu.messages.wire import Proposal, View
    from go_ibft_tpu.obs import ledger as cost_ledger
    from go_ibft_tpu.sched import CoalescedDispatcher
    from go_ibft_tpu.verify.aggregate import multi_aggregate_check

    cost_ledger.enable(compile_log=compile_log)

    # Coalesced host recover flush over real seals.
    keys = [PrivateKey.from_seed(b"cost-report-%d" % i) for i in range(4)]
    src = ECDSABackend.static_validators({k.address: 1 for k in keys})
    backends = [ECDSABackend(k, src) for k in keys]
    view = View(height=1, round=0)
    phash = proposal_hash_of(Proposal(raw_proposal=b"cost report drain", round=0))
    seals = [
        extract_committed_seal(b.build_commit_message(phash, view))
        for b in backends
    ]
    sender_ok, seal_ok = CoalescedDispatcher(route="host").dispatch(
        [], [(phash, seal) for seal in seals]
    )
    assert seal_ok.all(), "drain verdicts wrong — refusing to report"

    # Batched host multi-pairing over a real aggregate lane.
    blk = [hbls.BLSPrivateKey.from_seed(b"cost-report-%d" % i) for i in range(2)]
    lanes = [
        (phash, [k.sign(phash) for k in blk], [k.pubkey for k in blk])
    ] * 2
    assert multi_aggregate_check(lanes, route="host").all()
    return cost_ledger.snapshot()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--snapshot", default="cost_ledger.json")
    parser.add_argument("--compile-ledger", default="compile_ledger.jsonl")
    parser.add_argument("--evidence", default=None)
    parser.add_argument(
        "--drain",
        action="store_true",
        help="run a small in-process host drain and report its ledger "
        "(ignores --snapshot)",
    )
    parser.add_argument("--top", type=int, default=20)
    parser.add_argument(
        "--check",
        action="store_true",
        help="CI smoke: fail unless the report renders, every pinned "
        "family with recorded dispatches appears, and attribution "
        "meets --min-attribution",
    )
    parser.add_argument("--min-attribution", type=float, default=0.95)
    args = parser.parse_args()

    if args.drain:
        snap = run_drain(compile_log=args.compile_ledger)
        source = "in-process drain"
    else:
        try:
            with open(args.snapshot) as fh:
                snap = json.load(fh)
        except (OSError, ValueError) as err:
            print(
                f"cost_report: cannot read snapshot {args.snapshot!r} "
                f"({err}); run `python bench.py` (writes cost_ledger.json) "
                "or use --drain",
                file=sys.stderr,
            )
            return 2
        source = args.snapshot

    families = pinned_families()
    # --check asserts every pinned family that ran APPEARS in the
    # rendered table — so check mode never truncates (a healthy run with
    # many (program, route) rows must not fail on table length alone).
    if args.check:
        args.top = max(args.top, len(snap.get("dispatches", [])))
    report = render_snapshot(snap, top=args.top, families=families)
    print(f"cost report — source: {source}")
    print(report)
    if os.path.exists(args.compile_ledger):
        print()
        print(render_compile_ledger(args.compile_ledger))
    if args.evidence:
        print()
        print(render_evidence(args.evidence))

    if args.check:
        rows = snap.get("dispatches", [])
        total = sum(r["dispatches"] for r in rows)
        if total == 0:
            print("cost_report --check: FAIL (no dispatches recorded)")
            return 1
        ran = {r["program"] for r in rows if r["program"] in families}
        rendered = {r["program"] for r in rows[: args.top]}
        missing = [f for f in sorted(ran) if f not in rendered]
        attributed = sum(
            r["dispatches"] for r in rows if r["program"] in families
        )
        fraction = attributed / total
        failures = []
        if missing:
            failures.append(f"pinned families missing from report: {missing}")
        if fraction < args.min_attribution:
            failures.append(
                f"attribution {fraction:.1%} < {args.min_attribution:.0%}"
            )
        if failures:
            print("cost_report --check: FAIL")
            for failure in failures:
                print(f"  - {failure}")
            return 1
        print(
            f"cost_report --check: ok ({len(ran)} pinned families, "
            f"attribution {fraction:.1%})"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
