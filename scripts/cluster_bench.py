"""Thin driver for the cluster simulation engine (bench config #15).

The measurement of record is ``python bench.py --cluster-only`` (the
`make cluster-bench` entry point): oracle-gated chain identity, the >=3x
lock-step vs loopback bar, the 1000-validator one-dispatch structural
tick, and the evidence/ledger plumbing all live there.  This script is
the exploratory complement — one cluster, one transport, one JSON line —
for quick sweeps (``--nodes 256 --heights 3``) and chaos-schedule
spot-checks (``--seed`` prints the CHAOS-REPLAY line) without the bench
contract's budget machinery.

Usage: ``python scripts/cluster_bench.py [--nodes 100] [--heights 5]
[--transport lockstep|loopback] [--seed N] [--drop-rate 0.05]
[--round-timeout 5.0]``

On CPU, export ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
first (the Makefile target does) so the lock-step hub shards across
virtual devices instead of degrading to the host route.
"""

import argparse
import json
import sys

sys.path.insert(0, ".")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100)
    ap.add_argument("--heights", type=int, default=5)
    ap.add_argument(
        "--transport", choices=("lockstep", "loopback"), default="lockstep"
    )
    ap.add_argument(
        "--seed",
        type=int,
        default=None,
        help="enable the chaos mask plane with this seed (lock-step only); "
        "prints the run's CHAOS-REPLAY line",
    )
    ap.add_argument("--drop-rate", type=float, default=0.05)
    ap.add_argument(
        "--round-timeout",
        type=float,
        default=5.0,
        help="engine round timeout; at 100+ nodes keep this generous so "
        "the clean path stays on round 0 (docs/CLUSTER.md)",
    )
    ap.add_argument("--max-msgs", type=int, default=8)
    ap.add_argument("--max-bytes", type=int, default=1024)
    args = ap.parse_args()

    from go_ibft_tpu.sim import ChaosMask, ClusterSim, LoopbackClusterSim

    chaos = None
    if args.seed is not None:
        # Loss confined to a minority of receivers keeps the connected
        # majority's liveness provable (go_ibft_tpu/sim/chaos.py).
        lossy = tuple(range(max(1, args.nodes // 10)))
        chaos = ChaosMask(
            args.nodes, seed=args.seed,
            drop_rate=args.drop_rate, lossy=lossy,
        )

    if args.transport == "lockstep":
        sim = ClusterSim(
            args.nodes,
            max_msgs=args.max_msgs,
            max_bytes=args.max_bytes,
            round_timeout=args.round_timeout,
            chaos=chaos,
        )
    else:
        sim = LoopbackClusterSim(
            args.nodes, round_timeout=args.round_timeout
        )
    participants = (
        None
        if chaos is None
        else [i for i in range(args.nodes) if i not in set(chaos.lossy)]
    )
    kw = {} if args.transport == "loopback" else {"participants": participants}
    result = sim.run_sync(args.heights, **kw)

    line = {
        "metric": "cluster_heights_per_sec",
        "value": round(result.heights_per_s, 2),
        "unit": "heights/sec",
        "vs_baseline": None,
        "nodes": result.nodes,
        "heights": result.heights,
        "transport": result.transport,
        "ticks": result.ticks,
        "messages_per_tick": round(result.messages_per_tick, 1),
        "missed_heights": result.missed_heights(participants),
        "diverged_chains": result.diverged_chains(participants),
        "elapsed_s": round(result.elapsed_s, 3),
        "note": "exploratory sweep; the contract run is "
        "`python bench.py --cluster-only` (make cluster-bench)",
    }
    if chaos is not None:
        line["chaos_replay"] = chaos.replay_line(result.ticks)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
