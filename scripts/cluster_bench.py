"""End-to-end cluster throughput: N validators finalizing H heights.

The engine-level complement to bench.py's kernel-level configs: spins up a
full in-process cluster (every node runs the real asyncio state machine)
and measures heights/sec over either transport backend:

* ``loopback``   — direct in-process multicast (the reference's test
                   topology, go-ibft core/helpers_test.go:227-231);
* ``ici``        — the lock-step collective transport: one validator per
                   mesh device, multicast = one fixed-shape all_gather per
                   step (needs >= N devices; on CPU set
                   ``XLA_FLAGS=--xla_force_host_platform_device_count=N``).

Usage: ``python scripts/cluster_bench.py [--nodes 4] [--heights 5]
[--transport loopback|ici] [--crypto]``

``--crypto`` switches the mock backend for real ECDSA signing/verification
(host path; attach a device verifier through bench.py's configs instead
when measuring kernels — this script measures the *consensus runtime*).
"""

import argparse
import asyncio
import json
import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "tests")


def _build_engines(n: int, crypto: bool):
    from go_ibft_tpu.core import IBFT

    if crypto:
        from go_ibft_tpu.crypto import PrivateKey
        from go_ibft_tpu.crypto.backend import ECDSABackend

        keys = [PrivateKey.from_seed(b"cluster-bench-%d" % i) for i in range(n)]
        src = ECDSABackend.static_validators({k.address: 1 for k in keys})
        backends = [ECDSABackend(k, src) for k in keys]
    else:
        from harness import MockBackend

        class _Shim:
            def __init__(self, addresses):
                self.addresses = list(addresses)

                class _N:
                    def __init__(self, a):
                        self.address = a

                self.nodes = [_N(a) for a in self.addresses]

            def proposer_for(self, height, round_):
                return self.addresses[(height + round_) % len(self.addresses)]

        shim = _Shim([b"node-%02d-pad-pad-pad" % i for i in range(n)])
        backends = [MockBackend(a, shim) for a in shim.addresses]

    class _Null:
        def info(self, *a):
            pass

        debug = error = info

    engines = []
    for b in backends:
        e = IBFT(_Null(), b, None)
        e.set_base_round_timeout(10.0)
        engines.append(e)
    return engines


async def _run(engines, heights: int, transport: str) -> float:
    from go_ibft_tpu.core.transport import LoopbackTransport

    hub = None
    if transport == "ici":
        from go_ibft_tpu.net import IciLockstepTransport

        hub = IciLockstepTransport(len(engines), step_interval=0.001)
        for e in engines:
            e.transport = hub.register(e.add_messages)
        hub.start()
    else:
        loop = LoopbackTransport()
        for e in engines:
            loop.register(e.add_message)
            e.transport = loop

    t0 = time.perf_counter()
    try:
        for h in range(1, heights + 1):
            await asyncio.wait_for(
                asyncio.gather(*(e.run_sequence(h) for e in engines)), 120
            )
    finally:
        if hub is not None:
            await hub.stop()
        for e in engines:
            e.messages.close()
    elapsed = time.perf_counter() - t0
    for e in engines:
        assert len(e.backend.inserted) == heights, "a node missed a height"
    return elapsed


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--heights", type=int, default=5)
    ap.add_argument("--transport", choices=("loopback", "ici"), default="loopback")
    ap.add_argument("--crypto", action="store_true")
    ap.add_argument(
        "--platform",
        default=None,
        help="pin the jax platform (e.g. cpu); for --transport ici on CPU "
        "this also forces nodes-many virtual devices.  Env vars are not "
        "authoritative in containers with a sitecustomize hook — only "
        "jax.config.update before backend init works.",
    )
    args = ap.parse_args()

    if args.platform or args.transport == "ici":
        import jax

        try:
            jax.config.update("jax_platforms", args.platform or "cpu")
            if (args.platform or "cpu") == "cpu":
                jax.config.update("jax_num_cpu_devices", args.nodes)
        except RuntimeError:
            pass  # backend already initialized; keep whatever is live

    engines = _build_engines(args.nodes, args.crypto)
    elapsed = asyncio.run(_run(engines, args.heights, args.transport))
    print(
        json.dumps(
            {
                "metric": "cluster_heights_per_sec",
                "value": round(args.heights / elapsed, 2),
                "unit": "heights/sec",
                "vs_baseline": None,
                "nodes": args.nodes,
                "heights": args.heights,
                "transport": args.transport,
                "crypto": bool(args.crypto),
                "elapsed_s": round(elapsed, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
