"""A/B: XLA-scan keccak-f vs the Pallas (50, B) register-native kernel.

Decides the Pallas kernel's fate with data (VERDICT r03 weak #4): run on a
live TPU backend and compare p50s at consensus-relevant batch sizes.  On
CPU the Pallas kernel runs in interpret mode — those numbers say nothing
about TPU perf, but the run still proves the kernel TRACES and matches the
XLA route bit-for-bit (the CI forced-host mode, ISSUE 7 satellite: the
kernel had never executed in any mode before this job existed).

If Pallas itself is unavailable on the pinned jax (import failure, missing
interpret support), the script SKIPS with an explicit reason and exit
code 0 — an environment gap is not a parity failure.

Usage: python scripts/ab_keccak.py [--sizes 100,200,1000] [--reps 30] [--cpu]
"""

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The baseline arm times keccak_f's XLA-scan path; with GO_IBFT_PALLAS
# exported (the very flag under evaluation) keccak_f would route BOTH arms
# to the Pallas kernel and the A/B would compare it against itself.
os.environ.pop("GO_IBFT_PALLAS", None)


def log(**kw):
    print(json.dumps(kw), flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="100,200,1000")
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--cpu", action="store_true", help="pin CPU (interpret mode)")
    args = ap.parse_args(argv)

    import jax

    if args.cpu:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass

    import jax.numpy as jnp
    import numpy as np

    from go_ibft_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()

    from go_ibft_tpu.ops.keccak import keccak_f

    try:
        from go_ibft_tpu.ops.pallas_keccak import (
            keccak_f_pallas,
            pallas_supported,
        )
    except Exception as err:  # noqa: BLE001 - pallas missing on this jax
        log(
            skipped="pallas unavailable on the pinned jax",
            reason=f"{type(err).__name__}: {err}"[:200],
        )
        return 0

    platform = jax.devices()[0].platform
    interpret = not pallas_supported()

    log(platform=platform, pallas_interpret=interpret)

    def med(fn, *xs):
        jax.block_until_ready(fn(*xs))
        ts = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*xs))
            ts.append((time.perf_counter() - t0) * 1e3)
        return round(statistics.median(ts), 4)

    xla = jax.jit(keccak_f)
    pal = jax.jit(lambda st: keccak_f_pallas(st, interpret=interpret))

    rng = np.random.default_rng(7)
    for b in (int(s) for s in args.sizes.split(",")):
        state = jnp.asarray(
            rng.integers(0, 2**32, (b, 25, 2), dtype=np.uint32)
        )
        try:
            p = med(pal, state)
        except Exception as err:  # noqa: BLE001 - kernel cannot trace/run
            # Pallas IMPORTED but the kernel failed to trace/execute:
            # that is a regression of exactly the property this gate
            # exists to hold (the kernel must at least run in interpret
            # mode), not an environment gap — fail the job.
            log(
                error="pallas kernel failed to compile/run",
                batch=b,
                reason=f"{type(err).__name__}: {err}"[:200],
            )
            return 1
        x = med(xla, state)
        # parity gate: same permutation
        assert (np.asarray(xla(state)) == np.asarray(pal(state))).all(), (
            f"pallas kernel diverges from the XLA route at batch {b}"
        )
        log(batch=b, xla_scan_ms=x, pallas_ms=p, speedup=round(x / p, 2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
