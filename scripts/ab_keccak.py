"""A/B: XLA-scan keccak-f vs the Pallas (50, B) register-native kernel.

Decides the Pallas kernel's fate with data (VERDICT r03 weak #4): run on a
live TPU backend and compare p50s at consensus-relevant batch sizes.  On
CPU the Pallas kernel runs in interpret mode — those numbers say nothing
about TPU; the script labels the platform on every line.

Usage: python scripts/ab_keccak.py [--sizes 100,200,1000] [--reps 30]
"""

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, ".")

# The baseline arm times keccak_f's XLA-scan path; with GO_IBFT_PALLAS
# exported (the very flag under evaluation) keccak_f would route BOTH arms
# to the Pallas kernel and the A/B would compare it against itself.
os.environ.pop("GO_IBFT_PALLAS", None)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="100,200,1000")
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--cpu", action="store_true", help="pin CPU (interpret mode)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass

    import jax.numpy as jnp
    import numpy as np

    from go_ibft_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()

    from go_ibft_tpu.ops.keccak import keccak_f
    from go_ibft_tpu.ops.pallas_keccak import keccak_f_pallas, pallas_supported

    platform = jax.devices()[0].platform
    interpret = not pallas_supported()

    def log(**kw):
        print(json.dumps(kw), flush=True)

    log(platform=platform, pallas_interpret=interpret)

    def med(fn, *xs):
        jax.block_until_ready(fn(*xs))
        ts = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*xs))
            ts.append((time.perf_counter() - t0) * 1e3)
        return round(statistics.median(ts), 4)

    xla = jax.jit(keccak_f)
    pal = jax.jit(lambda st: keccak_f_pallas(st, interpret=interpret))

    rng = np.random.default_rng(7)
    for b in (int(s) for s in args.sizes.split(",")):
        state = jnp.asarray(
            rng.integers(0, 2**32, (b, 25, 2), dtype=np.uint32)
        )
        x = med(xla, state)
        p = med(pal, state)
        # parity gate: same permutation
        assert (np.asarray(xla(state)) == np.asarray(pal(state))).all()
        log(batch=b, xla_scan_ms=x, pallas_ms=p, speedup=round(x / p, 2))


if __name__ == "__main__":
    main()
