#!/usr/bin/env python
"""Reconstruct a cross-node consensus timeline from flight-recorder traces.

Usage::

    python scripts/consensus_timeline.py node0.json [node1.json ...]
        [--quorum N] [--perfetto merged.json] [--json timeline.json]

Inputs are ``obs/export.py`` Chrome-trace documents — either one
multi-track loopback export or N per-node exports from a cross-process
deployment (their clocks are aligned via the per-file
``otherData.clockOffsetsUs`` estimates).  Prints the per-height critical
path (which validator's message completed each quorum, the time split
between proposal broadcast / PREPARE quorum / COMMIT quorum / finalize
tail with verify, drain, and wakeup attribution) and optionally writes a
merged multi-process Perfetto file plus the raw reconstruction as JSON.

Exit code 0 when at least one height reconstructed, 2 when the traces
hold no consensus traffic (e.g. tracing was enabled but no ``net.send``
records landed), 1 on unreadable input.  A nonzero per-file
``droppedRecords`` is surfaced as a warning — a wrapped ring means the
window is NOT a complete record and early heights may be missing.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from go_ibft_tpu.obs import timeline  # noqa: E402


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("traces", nargs="+", help="per-node trace JSON files")
    parser.add_argument(
        "--quorum",
        type=int,
        default=None,
        help="quorum size (default: derived from the node count, equal powers)",
    )
    parser.add_argument(
        "--perfetto",
        metavar="OUT_JSON",
        default=None,
        help="write the merged multi-process Perfetto document here",
    )
    parser.add_argument(
        "--json",
        metavar="OUT_JSON",
        default=None,
        help="write the reconstructed timelines (one dict per height) here",
    )
    args = parser.parse_args()

    files = []
    for path in args.traces:
        try:
            files.append(timeline.load_trace_file(path))
        except (OSError, ValueError, KeyError) as err:
            print(f"consensus_timeline: cannot parse {path!r}: {err}", file=sys.stderr)
            return 1
    for trace_file in files:
        if trace_file.dropped:
            print(
                f"WARNING: {trace_file.path} dropped {trace_file.dropped} "
                "records (ring wrapped) — the timeline window is incomplete",
                file=sys.stderr,
            )

    merged = timeline.merge_events(files)
    timelines = timeline.reconstruct(merged, quorum=args.quorum)

    if args.perfetto:
        with open(args.perfetto, "w") as fh:
            json.dump(timeline.to_perfetto(files), fh)
        print(f"perfetto: {args.perfetto}", file=sys.stderr)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump([tl.to_dict() for tl in timelines], fh, indent=2)

    if not timelines:
        print(
            "consensus_timeline: no consensus traffic in the given traces "
            "(was tracing enabled during the run?)",
            file=sys.stderr,
        )
        return 2
    print(timeline.render_report(timelines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
