#!/usr/bin/env python
"""SLO gate report: grade soak-emitted SLO records pass/warn/fail.

Usage::

    python scripts/slo_gates.py [--slo slo.jsonl ...] [--fail-on fail|warn|never]

Parses one or more SLO JSONL files (the chain soak, the chaos matrix, and
``scripts/timeline_smoke.py`` append records when ``GO_IBFT_SLO_PATH`` is
set — or pass explicit paths) and grades every record against its limits
(per-record ``warn``/``fail`` fields win; ``obs/gates.py::
DEFAULT_SLO_TABLE`` supplies the standing ones).  Liveness SLOs like
``missed_heights`` are absolute contracts: ANY breach fails CI the same
way a perf regression does (``make slo-gates``).

Exit code: 0 unless a row at or above ``--fail-on`` severity exists
(default ``fail``); 2 when no records could be read at all.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from go_ibft_tpu.obs import gates  # noqa: E402


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--slo",
        action="append",
        default=None,
        metavar="PATH",
        help="SLO JSONL file(s); default $GO_IBFT_SLO_PATH or slo.jsonl",
    )
    parser.add_argument(
        "--fail-on",
        choices=("fail", "warn", "never"),
        default="fail",
        help="lowest severity that makes the exit code nonzero",
    )
    args = parser.parse_args()

    paths = args.slo or [os.environ.get("GO_IBFT_SLO_PATH") or "slo.jsonl"]
    records = []
    for path in paths:
        try:
            records.extend(gates.parse_slo_records(path))
        except OSError as err:
            print(f"slo_gates: cannot read {path!r} ({err})", file=sys.stderr)
    if not records:
        print(
            "slo_gates: no SLO records found — run a soak with "
            "GO_IBFT_SLO_PATH set (make timeline-smoke / make chain-soak)",
            file=sys.stderr,
        )
        return 2

    results = gates.gate_slo_records(records)
    print(gates.render_table(results))
    statuses = {r.status for r in results}
    bad = {"fail"} if args.fail_on == "fail" else {"fail", "warn"}
    if args.fail_on != "never" and statuses & bad:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
