"""Profile the host-side packing edge (messages -> device-ready arrays).

The fused kernels are only as fast as the host edge that feeds them: if
packing a 1000-message round costs more than the kernel, the end-to-end
p50 is host-bound.  This script times each packing stage separately so
optimization effort lands where the time actually goes.
"""

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402 - sys.path setup must precede package imports

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 300

    from go_ibft_tpu.bench.workload import _keys
    from go_ibft_tpu.crypto.backend import ECDSABackend, proposal_hash_of
    from go_ibft_tpu.messages.helpers import extract_committed_seal
    from go_ibft_tpu.messages.wire import Proposal, View
    from go_ibft_tpu.verify.batch import (
        pack_seal_batch,
        pack_sender_batch,
        pack_validator_table,
    )

    keys = _keys(n, 0)
    src = ECDSABackend.static_validators({k.address: 1 for k in keys})
    backends = [ECDSABackend(k, src) for k in keys]
    view = View(height=1, round=0)
    phash = proposal_hash_of(Proposal(raw_proposal=b"profile block", round=0))

    t0 = time.perf_counter()
    prepares = [b.build_prepare_message(phash, view) for b in backends]
    seals = [
        extract_committed_seal(b.build_commit_message(phash, view))
        for b in backends
    ]
    t_build = time.perf_counter() - t0

    t0 = time.perf_counter()
    payloads = [m.encode(include_signature=False) for m in prepares]
    t_encode = time.perf_counter() - t0

    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        pack_sender_batch(prepares)
    t_sender = (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    for _ in range(reps):
        pack_seal_batch(phash, seals)
    t_seal = (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    for _ in range(reps):
        pack_validator_table([k.address for k in keys])
    t_table = (time.perf_counter() - t0) / reps

    print(f"n={n}")
    print(f"  build+sign (one-time)     : {t_build * 1e3:9.2f} ms")
    print(f"  wire encode (per pack)    : {t_encode * 1e3:9.2f} ms [{len(payloads[0])}B each]")
    print(f"  pack_sender_batch         : {t_sender * 1e3:9.2f} ms")
    print(f"  pack_seal_batch           : {t_seal * 1e3:9.2f} ms")
    print(f"  pack_validator_table      : {t_table * 1e3:9.2f} ms")


if __name__ == "__main__":
    main()
