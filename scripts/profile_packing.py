"""Profile the host-side packing edge (messages -> device-ready arrays).

The fused kernels are only as fast as the host edge that feeds them: if
packing a 1000-message round costs more than the kernel, the end-to-end
p50 is host-bound.  This script times each packing stage separately so
optimization effort lands where the time actually goes, and diffs the
vectorized packers against the kept per-message reference loops
(``_pack_*_reference``) — the before/after evidence quoted in
docs/PERFORMANCE.md's "Packing & pipelining" section.
"""

import sys
import time

sys.path.insert(0, ".")

import jax  # noqa: E402 - sys.path setup must precede package imports

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass


def _timed(fn, reps: int = 5) -> float:
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 300

    from go_ibft_tpu.bench.workload import _keys
    from go_ibft_tpu.crypto.backend import ECDSABackend, proposal_hash_of
    from go_ibft_tpu.messages.helpers import extract_committed_seal
    from go_ibft_tpu.messages.wire import Proposal, View
    from go_ibft_tpu.verify.batch import (
        _pack_seal_batch_reference,
        _pack_sender_batch_reference,
        pack_seal_batch,
        pack_sender_batch,
        pack_validator_table,
    )
    from go_ibft_tpu.verify.pipeline import PackCache

    keys = _keys(n, 0)
    src = ECDSABackend.static_validators({k.address: 1 for k in keys})
    backends = [ECDSABackend(k, src) for k in keys]
    view = View(height=1, round=0)
    phash = proposal_hash_of(Proposal(raw_proposal=b"profile block", round=0))

    t0 = time.perf_counter()
    prepares = [b.build_prepare_message(phash, view) for b in backends]
    seals = [
        extract_committed_seal(b.build_commit_message(phash, view))
        for b in backends
    ]
    t_build = time.perf_counter() - t0

    t0 = time.perf_counter()
    payloads = [m.encode(include_signature=False) for m in prepares]
    t_encode = time.perf_counter() - t0

    # Apples-to-apples pure packing (payloads pre-encoded for both sides).
    t_ref = _timed(lambda: _pack_sender_batch_reference(prepares, payloads=payloads))
    t_vec = _timed(lambda: pack_sender_batch(prepares, payloads=payloads))

    # Full pack including the wire encode (what a cold drain pays) ...
    t_ref_full = _timed(lambda: _pack_sender_batch_reference(prepares))
    t_vec_full = _timed(lambda: pack_sender_batch(prepares))
    # ... and the steady-state engine shape: pack-cache warm, no re-encode.
    cache = PackCache()
    pack_sender_batch(prepares, cache=cache)
    t_cached = _timed(lambda: pack_sender_batch(prepares, cache=cache))

    t_seal_ref = _timed(lambda: _pack_seal_batch_reference(phash, seals))
    t_seal_vec = _timed(lambda: pack_seal_batch(phash, seals))
    t_table = _timed(lambda: pack_validator_table([k.address for k in keys]))

    print(f"n={n}")
    print(f"  build+sign (one-time)          : {t_build * 1e3:9.2f} ms")
    print(
        f"  wire encode (per cold pack)    : {t_encode * 1e3:9.2f} ms"
        f" [{len(payloads[0])}B each]"
    )
    print("  pack_sender_batch (pure pack, payloads given)")
    print(f"    reference loop               : {t_ref * 1e3:9.2f} ms")
    print(
        f"    vectorized                   : {t_vec * 1e3:9.2f} ms"
        f"   ({t_ref / t_vec:5.1f}x)"
    )
    print("  pack_sender_batch (full: encode + pack)")
    print(f"    reference loop               : {t_ref_full * 1e3:9.2f} ms")
    print(
        f"    vectorized                   : {t_vec_full * 1e3:9.2f} ms"
        f"   ({t_ref_full / t_vec_full:5.1f}x)"
    )
    print(
        f"    vectorized + warm pack cache : {t_cached * 1e3:9.2f} ms"
        f"   ({t_ref_full / t_cached:5.1f}x)"
    )
    print("  pack_seal_batch")
    print(f"    reference loop               : {t_seal_ref * 1e3:9.2f} ms")
    print(
        f"    vectorized                   : {t_seal_vec * 1e3:9.2f} ms"
        f"   ({t_seal_ref / t_seal_vec:5.1f}x)"
    )
    print(f"  pack_validator_table           : {t_table * 1e3:9.2f} ms")


if __name__ == "__main__":
    main()
