#!/usr/bin/env python
"""Telemetry-plane smoke: live endpoints + timeline + SLO gates in one run.

The CI entry (`make timeline-smoke`) for the cross-process telemetry
plane.  Boots a 4-node loopback ChainRunner cluster with tracing and the
fixed-bucket histograms ON and the /metrics,/healthz,/statusz endpoints
mounted on node 0, then — while the chain is still finalizing heights —
scrapes all three endpoints and validates them (Prometheus text parses,
healthz is 200/ok, statusz carries the pinned schema).  After the run it
exports the flight recorder, reconstructs the per-height consensus
timeline (`go_ibft_tpu.obs.timeline`), emits SLO records
(missed_heights, finalize p99, quarantine/shed counts) and grades them
through the SLO gates.  Exit 0 iff every step held.

    python scripts/timeline_smoke.py [--nodes 4] [--heights 3]
        [--trace-out DIR] [--slo-out slo.jsonl]
"""

import argparse
import asyncio
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _Null:
    def info(self, *a):
        pass

    debug = error = info


def _scrape(url: str):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read().decode("utf-8")


STATUSZ_REQUIRED = (
    "node",
    "height",
    "round",
    "state",
    "chain_height",
    "heights_run",
    "breaker_level",
    "speculation",
    "ring_dropped",
)


async def _run(args, tmp: str) -> int:
    from go_ibft_tpu.chain import ChainRunner, WriteAheadLog
    from go_ibft_tpu.core import IBFT, BatchingIngress
    from go_ibft_tpu.crypto import PrivateKey
    from go_ibft_tpu.crypto.backend import ECDSABackend
    from go_ibft_tpu.obs import gates, timeline, trace
    from go_ibft_tpu.obs.metrics_export import parse_exposition
    from go_ibft_tpu.utils import metrics
    from go_ibft_tpu.verify import HostBatchVerifier

    n, heights = args.nodes, args.heights
    metrics.reset()
    trace.enable(1 << 18)
    keys = [PrivateKey.from_seed(b"tlsmoke-%d" % i) for i in range(n)]
    src = ECDSABackend.static_validators({k.address: 1 for k in keys})
    nodes = []

    def gossip(message):
        for _, ingress in nodes:
            ingress.submit(message)

    class _T:
        def multicast(self, message):
            gossip(message)

    runners = []
    for i, key in enumerate(keys):
        core = IBFT(
            _Null(),
            ECDSABackend(key, src),
            _T(),
            batch_verifier=HostBatchVerifier(src),
        )
        core.set_base_round_timeout(10.0)
        ingress = BatchingIngress(core.add_messages)
        nodes.append((core, ingress))
        runners.append(
            ChainRunner(core, WriteAheadLog(os.path.join(tmp, f"wal-{i}.jsonl")))
        )

    server = runners[0].start_telemetry(port=0)
    failures = []
    try:
        tasks = [
            asyncio.create_task(r.run(until_height=heights)) for r in runners
        ]

        # Scrape WHILE the chain finalizes (the acceptance criterion).
        for _ in range(2000):
            if runners[0].latest_height() >= 1:
                break
            await asyncio.sleep(0.005)
        loop = asyncio.get_running_loop()
        code, text = await loop.run_in_executor(
            None, _scrape, server.url + "/metrics"
        )
        series = parse_exposition(text)  # raises on malformed exposition
        if code != 200:
            failures.append(f"/metrics returned {code}")
        if not any(k.startswith("go_ibft_latency_") for k in series):
            failures.append("/metrics holds no go_ibft_latency_* series")
        code, text = await loop.run_in_executor(
            None, _scrape, server.url + "/healthz"
        )
        health = json.loads(text)
        if code != 200 or not health.get("ok"):
            failures.append(f"/healthz unhealthy mid-run: {health}")
        code, text = await loop.run_in_executor(
            None, _scrape, server.url + "/statusz"
        )
        status = json.loads(text)
        missing = [k for k in STATUSZ_REQUIRED if k not in status]
        if code != 200 or missing:
            failures.append(f"/statusz missing keys: {missing}")

        await asyncio.wait_for(asyncio.gather(*tasks), 120)
    finally:
        for core, ingress in nodes:
            ingress.close()
            core.messages.close()
        server.stop()

    # -- timeline reconstruction over the run's own trace ---------------
    trace_dir = args.trace_out or tmp
    trace_path = os.path.join(trace_dir, "timeline_smoke_trace.json")
    runners[0].export_trace(trace_path)
    trace_file = timeline.load_trace_file(trace_path)
    merged = timeline.merge_events([trace_file])
    timelines = timeline.reconstruct(merged)
    finalized = [
        tl for tl in timelines if tl.critical_node is not None
    ]
    if len(finalized) < heights:
        failures.append(
            f"timeline reconstructed {len(finalized)}/{heights} heights"
        )
    for tl in finalized:
        split = tl.to_dict()["critical_path"]
        if split["commit_completer"] is None:
            failures.append(f"height {tl.height}: no COMMIT quorum completer")
    print(timeline.render_report(timelines))
    print()

    # -- SLO records + gates ---------------------------------------------
    missed = sum(max(0, heights - len(r.chain)) for r in runners)
    p99 = metrics.percentile(
        metrics.get_histogram(("go-ibft", "chain", "height_ms")), 0.99
    )
    records = [
        gates.slo_record(
            "missed_heights", missed, context={"nodes": n, "heights": heights}
        ),
        gates.slo_record(
            "quarantined_lanes",
            metrics.get_counter(("go-ibft", "resilient", "quarantined_lanes")),
        ),
        gates.slo_record(
            "shed_lanes", metrics.get_counter(("go-ibft", "sched", "shed_lanes"))
        ),
    ]
    if p99 is None:
        # A run that recorded no height latencies would make the latency
        # SLO silently vacuous — that is a smoke failure, not a pass.
        failures.append("no chain height_ms samples recorded")
    else:
        records.append(
            gates.slo_record("finalize_p99_ms", p99, fail=60_000.0)
        )
    gates.append_slo_records(args.slo_out, records)
    results = gates.gate_slo_records(records)
    print(gates.render_table(results))
    if any(r.status == "fail" for r in results):
        failures.append("SLO gate failed")

    if failures:
        for failure in failures:
            print(f"FAIL: {failure}", file=sys.stderr)
        return 1
    print(
        f"\ntimeline smoke OK: {n} nodes x {heights} heights, "
        f"{len(series)} metric series, {len(finalized)} heights reconstructed"
    )
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--heights", type=int, default=3)
    parser.add_argument(
        "--trace-out", default=None, help="keep the trace export here"
    )
    parser.add_argument(
        "--slo-out",
        default=os.environ.get("GO_IBFT_SLO_PATH"),
        help="append SLO records here (JSONL; default $GO_IBFT_SLO_PATH)",
    )
    args = parser.parse_args()
    with tempfile.TemporaryDirectory() as tmp:
        return asyncio.run(_run(args, tmp))


if __name__ == "__main__":
    sys.exit(main())
