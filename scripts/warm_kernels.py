"""Pre-warm the expensive kernel compiles into the persistent XLA cache.

CI's slow tier runs each test under a per-test timeout; a cold-cache BLS
pairing or EC-ladder compile can exceed that budget on a weak host.  This
script runs compiles with NO per-test timeout so the subsequent pytest run
only pays cache loads.  Shapes warmed here are the ones the slow suites and
``bench.py`` actually dispatch (verifier buckets + bench workload buckets +
the pairing program + the Pallas interpret-mode keccak).

The ``XLA_FLAGS`` device-count flag is part of the persistent-cache key,
so this script force-matches tests/conftest.py's 8-virtual-device setup
BEFORE jax loads — warmed programs must be loadable by the test suite.

The pinned compile-budget families warm FIRST through the AOT program
store (``go_ibft_tpu/boot/aot.py``): families whose store sidecar says a
prior run already compiled them into this cache under the same
jax/backend/topology fingerprint are SKIPPED (``--no-skip`` forces a full
re-warm), so a second warm run costs seconds, not minutes.  The runtime
warm steps below it re-warm the extra non-pinned shapes (big buckets,
Pallas, multi-pairing lanes) every run — on a warm cache those are cache
loads, which is exactly the cheap path.

Usage: ``python scripts/warm_kernels.py [--skip-bls] [--skip-mesh]
[--skip-aot] [--aot-only] [--no-skip] [--programs a,b] [--assert-warm]
[--manifest out.json] [--sizes 8,100,...]``

* ``--manifest out.json`` — write the machine-readable AOT manifest
  (fingerprint + per-family measured compile cost) that
  ``python -m go_ibft_tpu.boot --manifest`` / ``warm_start(manifest=)``
  consume to select their restore set;
* ``--aot-only [--programs k1,k2]`` — restore just the (selected) pinned
  families through the AOT store and exit: the fast CI boot check;
* ``--assert-warm`` — exit non-zero if the AOT restore classified ANY
  program as a cold compile: run twice against the same cache dir and
  the second run proves the cache (the CI ``boot-check`` gate).
"""

import os
import sys
import time

sys.path.insert(0, ".")

# Must match tests/conftest.py (same flag => same persistent-cache key).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Default sizes cover the slow-tier suites + the bench CPU-fallback path
# (8-lane engine bucket, 100-validator headline bucket).  The 300/1000
# configs only run on a live TPU, where compiles happen on-chip against
# the TPU cache key — CPU-warming them costs ~an hour each for nothing;
# opt in with --sizes 8,100,300,1000 when needed.
_DEFAULT_SIZES = (8, 100)


def _argval(flag: str) -> str:
    for i, arg in enumerate(sys.argv):
        if arg == flag and i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return ""


def _sizes() -> tuple:
    val = _argval("--sizes")
    if val:
        return tuple(int(s) for s in val.split(","))
    return _DEFAULT_SIZES


def _stamp(label: str, t0: float, program: str = None) -> None:
    """Print the step duration; with ``program`` set, ALSO record it to
    the compile ledger — used ONLY for steps whose kernels this script
    cannot introspect (the mesh dryrun, the Pallas kernel).  Every other
    step's true compiles are recorded by the seam-level cache
    introspection inside the warmup()/entry-point it drives, so stamping
    those here would double-count each cold build (and record cache
    LOADS as compiles on a warm persistent cache)."""
    from go_ibft_tpu.obs import ledger as cost_ledger

    elapsed_s = time.perf_counter() - t0
    print(f"[warm] {label}: {elapsed_s:.1f}s", flush=True)
    if program is not None:
        cost_ledger.record_compile(
            program,
            elapsed_s * 1e3,
            site="scripts/warm_kernels.py (step duration, not introspected)",
        )


def _warm_aot_store() -> int:
    """Restore the pinned compile-budget families through the AOT store,
    skipping families a prior run already compiled into this cache (their
    sidecar fingerprint matches this process).  Returns the number of
    programs classified as COLD compiles (``--assert-warm`` evidence)."""
    from go_ibft_tpu.boot.aot import AOTStore

    store = AOTStore(site="scripts/warm_kernels.py (aot)")
    requested = _argval("--programs")
    programs = (
        [s for s in requested.split(",") if s]
        if requested
        else list(store.pinned_programs())
    )
    skipped = []
    if "--no-skip" not in sys.argv:
        cached = store.cached_programs()
        skipped = [p for p in programs if p in cached]
        programs = [p for p in programs if p not in cached]
    if skipped:
        print(
            f"[warm] aot: {len(skipped)} program(s) already cached "
            f"(sidecar fingerprint match) — skipped: {','.join(skipped)}",
            flush=True,
        )
    cold = 0
    if programs:
        t0 = time.perf_counter()
        statuses = store.ensure(programs)
        for name, st in statuses.items():
            print(
                f"[warm] aot: {name}: {st.status} "
                f"(compile {st.compile_ms / 1e3:.1f}s, "
                f"lower {st.lower_ms / 1e3:.1f}s)"
                + (f" — {st.reason}" if st.reason else ""),
                flush=True,
            )
        cold = sum(1 for st in statuses.values() if st.status == "cold")
        _stamp(f"AOT program store ({len(programs)} program(s))", t0)
    return cold


def _finish(cold: int) -> int:
    """The measured compile table + optional manifest, shared by the full
    warm flow and ``--aot-only``; returns the process exit code."""
    from go_ibft_tpu.obs import ledger as cost_ledger

    # The measured cold-compile (or cache-load) duration table, also
    # appended per event to compile_ledger.jsonl above — CI's archived
    # baseline for the ROADMAP-item-5 AOT compile cache.
    snap = cost_ledger.snapshot()
    if snap is not None and snap["compiles"]:
        print("[warm] compile ledger (per program):", flush=True)
        for name, acc in sorted(
            snap["compiles"].items(), key=lambda kv: -kv[1]["ms"]
        ):
            print(
                f"[warm]   {name}: {acc['count']} event(s), "
                f"{acc['ms'] / 1e3:.1f}s total",
                flush=True,
            )

    manifest_path = _argval("--manifest")
    if manifest_path:
        from go_ibft_tpu.boot.aot import AOTStore, family_of, write_manifest

        # Family-keyed measured costs: the store sidecars (authoritative
        # for the pinned set — they survive skip runs where the ledger
        # records nothing) overlaid with this run's ledger families (the
        # non-pinned extras the runtime steps compiled).
        store = AOTStore()
        programs: dict = {}
        for program in store.cached_programs():
            side = store.read_sidecar(program) or {}
            fam = programs.setdefault(
                family_of(program), {"compile_ms": 0.0, "events": 0}
            )
            fam["compile_ms"] += float(side.get("compile_ms", 0.0))
            fam["events"] += 1
        if snap is not None:
            for name, acc in snap["compiles"].items():
                fam = programs.setdefault(
                    name, {"compile_ms": 0.0, "events": 0}
                )
                fam["compile_ms"] += acc["ms"]
                fam["events"] += acc["count"]
        write_manifest(manifest_path, programs, sizes=_sizes())
        print(
            f"[warm] aot manifest: {manifest_path} "
            f"({len(programs)} families)",
            flush=True,
        )

    cost_ledger.disable()
    if cold and "--assert-warm" in sys.argv:
        print(
            f"[warm] FAIL --assert-warm: {cold} cold compile(s) on a cache "
            "that was supposed to be warm",
            flush=True,
        )
        return 2
    return 0


def main() -> int:
    from go_ibft_tpu.obs import ledger as cost_ledger
    from go_ibft_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()
    cost_ledger.enable(
        compile_log=os.environ.get(
            "GO_IBFT_COMPILE_LEDGER", "compile_ledger.jsonl"
        )
    )

    # Pinned families first, through the AOT store: everything below then
    # loads from the persistent cache instead of compiling cold.
    cold = 0
    if "--skip-aot" not in sys.argv:
        cold = _warm_aot_store()
    if "--aot-only" in sys.argv:
        return _finish(cold)

    import jax.numpy as jnp

    # bench.py owns the canonical argument packing for the fused kernels;
    # importing it keeps the warmed programs in lockstep with what the
    # bench and the engine actually dispatch.
    from bench import _prep_args, _round_args, _seal_args
    from go_ibft_tpu.bench import build_round_workload
    from go_ibft_tpu.ops.quorum import (
        quorum_certify,
        round_certify,
        seal_quorum_certify,
    )
    from go_ibft_tpu.verify import DeviceBatchVerifier

    # Mesh FIRST: MULTICHIP_r{N}.json is the artifact a cold cache kills
    # (r03 rc=124); everything after this line is cheaper to lose to a
    # budget cut than the dryrun programs.
    if "--skip-mesh" not in sys.argv:
        t0 = time.perf_counter()
        from __graft_entry__ import dryrun_multichip

        dryrun_multichip(8)
        _stamp("mesh dryrun programs (8-device (dp, vp))", t0, "mesh_quorum_certify")

        # MeshBatchVerifier's sharded mask program at the tier-1 test
        # shapes (dp=2 and dp=8, 8 local lanes, 8-row table): the oracle-
        # parity suite dispatches exactly these, and a cold shard_map
        # compile inside a test timeout is the same failure mode as the
        # dryrun's.
        import jax

        from go_ibft_tpu.parallel import mesh_context
        from go_ibft_tpu.verify import MeshBatchVerifier

        from go_ibft_tpu.boot.aot import AOTStore as _AOTStore

        _mask_cached = (
            set() if "--no-skip" in sys.argv else _AOTStore().cached_programs()
        )
        for dp in (2, 8):
            if f"mesh_verify_mask_8l_dp{dp}" in _mask_cached:
                # Exact pin match: the AOT store already restored this
                # shard_map program into this cache — skip the lowering.
                print(f"[warm] mask program (dp={dp}): cached, skipped", flush=True)
                continue
            t0 = time.perf_counter()
            mv = MeshBatchVerifier(
                lambda h: {}, mesh=mesh_context(dp, devices=jax.devices()[:dp])
            )
            if mv.sharded:
                mv.warmup()
                _stamp(f"MeshBatchVerifier mask program (dp={dp})", t0)

    t0 = time.perf_counter()
    DeviceBatchVerifier(lambda h: {}).warmup()
    _stamp("DeviceBatchVerifier buckets", t0)

    # Early-exit drain shapes (ISSUE 9): the power-ordered chunked seal
    # drain dispatches the recover kernel at the quorum-prefix chunk
    # bucket — (128 lanes, 128-row table) for a 100-validator committee,
    # and the (8, 128) shape the weighted-committee suites hit.  Cold-
    # compiling either inside a test timeout is the failure mode this
    # script exists to prevent.
    t0 = time.perf_counter()
    DeviceBatchVerifier(lambda h: {}).warmup(lanes=(8, 128), table_rows=128)
    _stamp("early-exit drain shapes (8/128 lanes x 128-row table)", t0)

    # Serve-path drain shapes (ISSUE 10): the proof-serving read plane's
    # device route is the multi-tenant CoalescedDispatcher — fresh proof
    # lanes coalesce into the SAME pinned recover/digest programs at the
    # claimed-signer-table shapes ((8, 8) for the tier-1 suites, (128,
    # 128) for a 100-validator quorum drain).  Cold-compiling either
    # inside a test or bench timeout is the failure mode warmed here.
    t0 = time.perf_counter()
    from go_ibft_tpu.sched import CoalescedDispatcher

    CoalescedDispatcher(route="device").warmup(lanes=(8, 128), table_rows=128)
    _stamp("serve/sched coalesced drain shapes (8/128 lanes)", t0)

    # Lock-step cluster tick collective (ISSUE 17): the rows variant at
    # the 8-node real-crypto shape is AOT-pinned above (ici_tick_8n);
    # this additionally warms the lite variant at the 100-validator
    # bench/soak shape (100 nodes -> 5-way shard over the 8 forced host
    # devices) so `make cluster-bench` and the tier-1 cluster soak never
    # pay the gather compile inside a timed window or per-test budget.
    t0 = time.perf_counter()
    import jax
    import numpy as _np_ici
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from go_ibft_tpu.net.ici import build_tick_program, shard_count

    _devs = jax.devices("cpu")
    _d = shard_count(100, len(_devs))
    if _d > 1:
        _mesh = Mesh(_np_ici.asarray(_devs[:_d]), ("node",))
        _prog = build_tick_program(_mesh)
        with cost_ledger.compile_watch(
            (("ici_tick", _prog),), site="scripts/warm_kernels.py"
        ):
            _staging = jax.device_put(
                jnp.zeros((100, 8, 1024), jnp.uint8),
                NamedSharding(_mesh, PartitionSpec("node")),
            )
            _prog(_staging).block_until_ready()
        _stamp("ici lock-step tick (100-node lite gather)", t0)

    for n in _sizes():
        t0 = time.perf_counter()
        w = build_round_workload(n)
        # The fused quorum programs are jit objects: the compile watch
        # records true first compiles (cache loads record nothing).
        with cost_ledger.compile_watch(
            (
                ("quorum_certify", quorum_certify),
                ("seal_quorum_certify", seal_quorum_certify),
                ("round_certify", round_certify),
            ),
            site="scripts/warm_kernels.py",
        ):
            quorum_certify(*_prep_args(w))[0].block_until_ready()
            seal_quorum_certify(*_seal_args(w))[0].block_until_ready()
            round_certify(*_round_args(w))[0].block_until_ready()
        _stamp(f"quorum kernels @{n} validators", t0)

    t0 = time.perf_counter()
    from go_ibft_tpu.ops.pallas_keccak import keccak_f_pallas, pallas_supported

    state = jnp.zeros((1, 25, 2), dtype=jnp.uint32)
    keccak_f_pallas(state, interpret=not pallas_supported()).block_until_ready()
    _stamp("pallas keccak_f (50x128 tile)", t0, "pallas_keccak_f")

    if "--skip-bls" not in sys.argv:
        t0 = time.perf_counter()
        from go_ibft_tpu.bench.bls_workload import build_bls_round_workload
        from go_ibft_tpu.ops.bls12_381 import aggregate_verify_commit

        w = build_bls_round_workload(4, time_host=False)
        aggregate_verify_commit(*w.args).block_until_ready()
        _stamp("BLS pairing program (4v bucket)", t0)
        w = build_bls_round_workload(100, time_host=False)
        aggregate_verify_commit(*w.args).block_until_ready()
        _stamp("BLS pairing program (100v bucket)", t0)

        # ISSUE 12: the device-resident aggregation shapes.  The merge
        # trees are SMALL programs (one scanned point-add body) at the
        # tier-1 test bucket (8) and the mega-committee bucket (128);
        # the batched multi-pairing warms at the tiny 2-lane bucket the
        # slow-tier parity test dispatches — its Miller stage is the
        # big compile, and the final-exp stages are the SAME jit
        # objects warmed by the pairing programs above (batched shapes
        # still recompile per lane count, which is exactly what this
        # warms).
        import numpy as _np

        from go_ibft_tpu.crypto import bls as _hbls
        from go_ibft_tpu.ops.bls12_381 import (
            g1_merge_tree,
            g2_merge_tree,
            pack_g1_points,
            pack_g2_points,
        )

        merge_watch = (
            ("bls_g2_merge_tree", g2_merge_tree),
            ("bls_g1_merge_tree", g1_merge_tree),
        )
        for bucket in (8, 128):
            t0 = time.perf_counter()
            pts = [_hbls.g2_mul(3 + i, _hbls.G2_GEN) for i in range(2)]
            x0, x1, y0, y1 = pack_g2_points(pts + [None] * (bucket - 2))
            live = _np.zeros(bucket, dtype=bool)
            live[:2] = True
            with cost_ledger.compile_watch(
                merge_watch, site="scripts/warm_kernels.py"
            ):
                jnp.asarray(
                    g2_merge_tree(
                        jnp.asarray(x0),
                        jnp.asarray(x1),
                        jnp.asarray(y0),
                        jnp.asarray(y1),
                        jnp.asarray(live),
                    )[0]
                ).block_until_ready()
                if bucket == 128:
                    g1 = [_hbls.g1_mul(3 + i, _hbls.G1_GEN) for i in range(2)]
                    px, py = pack_g1_points(g1 + [None] * (bucket - 2))
                    jnp.asarray(
                        g1_merge_tree(
                            jnp.asarray(px), jnp.asarray(py), jnp.asarray(live)
                        )[0]
                    ).block_until_ready()
            _stamp(f"g2/g1 merge-tree kernels ({bucket} bucket)", t0)

        t0 = time.perf_counter()
        from go_ibft_tpu.verify.aggregate import multi_aggregate_check

        wkeys = [_hbls.BLSPrivateKey.from_seed(b"warm-mp-%d" % i) for i in range(2)]
        wmsg = b"warm multipair lane" + b"\x00" * 13
        lanes = [
            (
                wmsg,
                [_hbls.aggregate_signatures([k.sign(wmsg) for k in wkeys])],
                [k.pubkey for k in wkeys],
            )
        ] * 2
        assert multi_aggregate_check(lanes, route="device").all()
        _stamp("batched multi-pairing (2-lane bucket)", t0)

        # Checkpoint skip-chain verify shape (ISSUE 20): a cold sync
        # verifies ~log2(epochs) certificate lanes in ONE dispatch —
        # warm the 4-lane bucket so the first CheckpointClient on a
        # device route never compiles mid-sync.
        t0 = time.perf_counter()
        ck_lanes = [
            (
                b"warm ckpt lane %03d" % i + b"\x00" * 13,
                [_hbls.aggregate_signatures(
                    [k.sign(b"warm ckpt lane %03d" % i + b"\x00" * 13)
                     for k in wkeys]
                )],
                [k.pubkey for k in wkeys],
            )
            for i in range(4)
        ]
        assert multi_aggregate_check(ck_lanes, route="device").all()
        _stamp("checkpoint skip-chain multi-pairing (4-lane bucket)", t0)

    return _finish(cold)


if __name__ == "__main__":
    sys.exit(main())
