"""Decompose the headline kernel's TPU cost, stage by stage.

Times each sub-program of the fused round certification on the active
backend (trivial dispatch, keccak digest, recovery ladder, full
``round_certify``) so regressions and optimizations can be attributed to a
stage instead of guessed at.  Writes one JSON line per probe.

Usage: python scripts/profile_decompose.py [--lanes N]
"""

import argparse
import json
import statistics
import sys
import time

sys.path.insert(0, ".")


def _probe_backend() -> bool:
    from go_ibft_tpu.utils.probe import probe_default_backend

    platform, _ = probe_default_backend()
    return platform is not None


def med(fn, *args, reps: int = 10) -> float:
    """p50 wall time forcing a real device->host readback each rep.

    ``block_until_ready`` alone can be a lazy ack on tunneled backends;
    materializing one element of the (possibly pytree) result on host is
    an end-to-end sync no transport can fake."""
    import jax
    import numpy as np

    def sync(out):
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(leaf).ravel()[:1]

    sync(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append((time.perf_counter() - t0) * 1e3)
    return round(statistics.median(ts), 3)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", type=int, default=100)
    ap.add_argument("--skip-probe", action="store_true")
    args = ap.parse_args()

    if not args.skip_probe and not _probe_backend():
        print(json.dumps({"probe": "backend", "ok": False}))
        sys.exit(1)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from go_ibft_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()

    from go_ibft_tpu.bench import build_round_workload
    from go_ibft_tpu.ops import fields
    from go_ibft_tpu.ops import keccak as dk
    from go_ibft_tpu.ops import quorum
    from go_ibft_tpu.ops import secp256k1 as sec

    def log(**kw):
        print(json.dumps(kw), flush=True)

    log(platform=jax.devices()[0].platform, lanes=args.lanes)

    w = build_round_workload(args.lanes)
    blocks, counts, pr, ps, pv, senders, plive = (
        jnp.asarray(a) for a in w.prepare
    )

    triv = jax.jit(lambda x: x + 1)
    log(stage="trivial_dispatch_ms", p50=med(triv, jnp.ones((8, 128), jnp.int32)))

    B = pr.shape[0]
    a = jnp.asarray(np.random.randint(0, 8191, (B, 20)).astype(np.int32))
    log(stage="field_mul_ms", p50=med(jax.jit(lambda x, y: fields.mul(sec.FIELD, x, y)), a, a))
    log(stage="field_inv_ms", p50=med(jax.jit(lambda x: fields.inv(sec.FIELD, x)), a))
    # r05 levers: the Montgomery product-tree inverse (one Fermat scan for
    # the whole batch) and the merged sqrt+inv dual scan.
    log(stage="batch_inv_ms", p50=med(jax.jit(lambda x: fields.batch_inv(sec.FIELD, x)), a))
    log(
        stage="pow_fixed2_ms",
        p50=med(
            jax.jit(
                lambda x, y: fields.pow_fixed2(
                    sec.FIELD, x, (sec.P + 1) // 4, sec.ORDER, y, sec.N - 2
                )
            ),
            a,
            a,
        ),
    )

    digest = jax.jit(quorum.digest_words)
    log(stage="digest_words_ms", p50=med(digest, blocks, counts))

    zw = digest(blocks, counts)
    z = jax.jit(lambda q: dk.words_le_to_limbs(q, sec.FIELD.nlimbs))(zw)

    qx = jnp.broadcast_to(jnp.asarray(sec.FIELD.const(sec.GX)), (B, 20))
    qy = jnp.broadcast_to(jnp.asarray(sec.FIELD.const(sec.GY)), (B, 20))
    log(stage="ecmul2_base_ms", p50=med(jax.jit(sec.ecmul2_base), pr, ps, qx, qy))
    # A/B: the pre-GLV Shamir ladder (64 steps, 2 streams) vs the GLV
    # ladder above (33 steps, 4 streams) — the r04 headline lever.
    log(
        stage="ecmul2_shamir_ms",
        p50=med(jax.jit(sec._ecmul2_base_shamir), pr, ps, qx, qy),
    )
    log(stage="glv_split_ms", p50=med(jax.jit(sec.glv_split), pr))

    log(stage="ecdsa_recover_ms", p50=med(jax.jit(sec.ecdsa_recover), z, pr, ps, pv))

    sig = jax.jit(quorum.sig_checks_zw)
    log(stage="sig_checks_zw_ms", p50=med(sig, zw, pr, ps, pv, senders, plive))

    cert = jax.jit(quorum.quorum_certify)
    pa = (
        blocks, counts, pr, ps, pv, senders,
        jnp.asarray(w.table), plive,
        jnp.asarray(w.powers_lo), jnp.asarray(w.powers_hi),
        jnp.int32(w.thr_lo), jnp.int32(w.thr_hi),
    )
    log(stage="quorum_certify_ms", p50=med(cert, *pa))


if __name__ == "__main__":
    main()
