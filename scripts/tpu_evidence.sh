#!/usr/bin/env bash
# One-shot TPU evidence suite (VERDICT r04 asks #1b, #2, #5, #6).
#
# Run when the tunneled TPU is live (probe first; a hanging tunnel eats
# GO_IBFT_PROBE_TIMEOUT once per process).  Order matters:
#   1. warm the TPU-keyed persistent cache (compiles happen HERE, not
#      inside timed sections),
#   2. stage attribution + design A/Bs (profile_decompose, ab_keccak,
#      ab_ladder_select),
#   3. the bench matrix last — it records the headline + calibration.
#
# Every step appends JSON lines to $OUT (default evidence_tpu.jsonl).
#
# Standing items (run when chip time allows, not yet wired as steps):
#   - on-chip ici tick capture: `python bench.py --cluster-only
#     --device-trace ici_tick_prof` on a real mesh — the lock-step
#     gather over ICI (not host-shard loopback) is the number the
#     cluster engine's 3x CPU evidence stands in for (ISSUE 17).
set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-evidence_tpu.jsonl}"

step() {
  echo "{\"evidence_step\": \"$1\", \"ts\": $(date +%s)}" | tee -a "$OUT"
  shift
  "$@" 2>&1 | tee -a "$OUT"
}

step warm_kernels   python scripts/warm_kernels.py --sizes 8,100,300,1000
step profile        python scripts/profile_decompose.py
step ab_keccak      python scripts/ab_keccak.py
step ab_ladder      python scripts/ab_ladder_select.py
step bench          python bench.py
echo "evidence complete -> $OUT"
