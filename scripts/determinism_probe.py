"""Determinism probe: verification results must be bit-stable across runs.

The reference's reproducible-build CI job builds the binary twice and
compares hashes (.github/workflows/main.yml:48-67).  The analogue for a
verification framework is result determinism: two fresh processes running
the same workload must produce byte-identical masks and quorum sums.
Printed as canonical JSON; CI `cmp`s two runs.
"""

import json
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    from go_ibft_tpu.bench import build_round_workload
    from go_ibft_tpu.ops.quorum import quorum_certify, seal_quorum_certify
    from go_ibft_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()
    import jax.numpy as jnp

    w = build_round_workload(8, corrupt_frac=0.25, seed=11)
    blocks, counts, r, s, v, senders, live = w.prepare
    mask, reached, lo, hi = quorum_certify(
        jnp.asarray(blocks),
        jnp.asarray(counts),
        jnp.asarray(r),
        jnp.asarray(s),
        jnp.asarray(v),
        jnp.asarray(senders),
        jnp.asarray(w.table),
        jnp.asarray(live),
        jnp.asarray(w.powers_lo),
        jnp.asarray(w.powers_hi),
        jnp.int32(w.thr_lo),
        jnp.int32(w.thr_hi),
    )
    hz, sr, ss_, sv, signers, slive = w.seals
    smask, sreached, slo, shi = seal_quorum_certify(
        jnp.asarray(hz),
        jnp.asarray(sr),
        jnp.asarray(ss_),
        jnp.asarray(sv),
        jnp.asarray(signers),
        jnp.asarray(w.table),
        jnp.asarray(slive),
        jnp.asarray(w.powers_lo),
        jnp.asarray(w.powers_hi),
        jnp.int32(w.thr_lo),
        jnp.int32(w.thr_hi),
    )
    # The single-dispatch whole-round program must be just as bit-stable.
    from go_ibft_tpu.ops.quorum import round_certify

    fmask, freached, fsmask, fsreached = round_certify(
        jnp.asarray(blocks),
        jnp.asarray(counts),
        jnp.asarray(r),
        jnp.asarray(s),
        jnp.asarray(v),
        jnp.asarray(senders),
        jnp.asarray(live),
        jnp.asarray(hz),
        jnp.asarray(sr),
        jnp.asarray(ss_),
        jnp.asarray(sv),
        jnp.asarray(signers),
        jnp.asarray(slive),
        jnp.asarray(w.table),
        jnp.asarray(w.powers_lo),
        jnp.asarray(w.powers_hi),
        jnp.int32(w.thr_lo),
        jnp.int32(w.thr_hi),
    )
    json.dump(
        {
            "prepare_mask": np.asarray(mask).tolist(),
            "prepare": [bool(np.asarray(reached)), int(lo), int(hi)],
            "seal_mask": np.asarray(smask).tolist(),
            "seal": [bool(np.asarray(sreached)), int(slo), int(shi)],
            "round_masks": np.asarray(fmask).tolist()
            + np.asarray(fsmask).tolist(),
            "round": [bool(np.asarray(freached)), bool(np.asarray(fsreached))],
        },
        sys.stdout,
        sort_keys=True,
    )
    sys.stdout.write("\n")


if __name__ == "__main__":
    main()
