"""Break the happy-path height latency into engine/crypto components.

The r05 round recorded the 4-validator happy path at 0.86x of the
sequential host baseline (33.9 ms/height) with no attribution — this
script is the profiler that turns that one number into a budget.  It runs
the same cluster shape as ``bench.py`` config #1 (4 validators, real
ECDSA, BatchingIngress gossip, adaptive verifier) with the hot seams
instrumented from OUTSIDE the engine:

* ``sign_ms``      — outbound envelope + seal signing (crypto.ecdsa.sign)
* ``verify_ms``    — inbound signature verification (batch verifier calls
                     + per-message backend predicates)
* ``hash_ms``      — proposal-hash recomputations (backend keccak)
* ``window_ms``    — time messages sat buffered in BatchingIngress before
                     their flush (the ingress window's latency cost)
* ``engine_ms``    — everything else on the wall clock: state machine,
                     store, signaling, event loop

Components are measured independently (sign/verify/hash nest inside the
height wall time; window overlaps the engine's awaits), so they are a
budget, not a partition.  Usage::

    python scripts/profile_hotpath.py [--validators 4] [--heights 7]

Prints one JSON object per run.  No device work: the 4-validator shape
routes to the native host path (the point of the adaptive cutover); pass
``--validators 100`` on a live backend to profile the device route, where
``verify_ms`` covers packing + dispatch + readback (see
``utils.metrics`` device observations printed alongside).
"""

import argparse
import asyncio
import json
import pathlib
import statistics
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


class Tally:
    """Named stopwatch accumulators."""

    def __init__(self) -> None:
        self.totals: dict = {}
        self.counts: dict = {}

    def add(self, key: str, seconds: float) -> None:
        self.totals[key] = self.totals.get(key, 0.0) + seconds

    def wrap(self, key: str, fn):
        def timed(*a, **kw):
            t0 = time.perf_counter()
            try:
                return fn(*a, **kw)
            finally:
                self.add(key, time.perf_counter() - t0)
                self.counts[key] = self.counts.get(key, 0) + 1

        return timed

    def ms(self, key: str) -> float:
        return round(self.totals.get(key, 0.0) * 1e3, 3)


def _patch_crypto(tally: Tally) -> None:
    from go_ibft_tpu.crypto import backend as cb

    cb.ec.sign = tally.wrap("sign", cb.ec.sign)


def _patch_verifier(tally: Tally, verifier) -> None:
    verifier.verify_senders = tally.wrap("verify", verifier.verify_senders)
    verifier.verify_committed_seals = tally.wrap(
        "verify", verifier.verify_committed_seals
    )


def _patch_backend(tally: Tally, backend) -> None:
    backend.is_valid_validator = tally.wrap("verify", backend.is_valid_validator)
    backend.is_valid_committed_seal = tally.wrap(
        "verify", backend.is_valid_committed_seal
    )
    backend.is_valid_proposal_hash = tally.wrap(
        "hash", backend.is_valid_proposal_hash
    )


class WindowedIngress:
    """BatchingIngress wrapper measuring buffered wall time per message."""

    def __init__(self, inner, tally: Tally) -> None:
        self._inner = inner
        self._tally = tally
        self._stamps: list = []
        inner_flush = inner.flush

        def flush():
            now = time.perf_counter()
            for t0 in self._stamps:
                tally.add("window", now - t0)
            self._stamps.clear()
            inner_flush()

        inner.flush = flush

    def submit(self, message) -> None:
        self._stamps.append(time.perf_counter())
        self._inner.submit(message)

    def close(self) -> None:
        self._stamps.clear()
        self._inner.close()


def run_profile(n_validators: int, heights: int) -> dict:
    from go_ibft_tpu.core import IBFT, BatchingIngress
    from go_ibft_tpu.crypto import PrivateKey
    from go_ibft_tpu.crypto.backend import ECDSABackend
    from go_ibft_tpu.verify import AdaptiveBatchVerifier

    tally = Tally()
    _patch_crypto(tally)

    class _Null:
        def info(self, *a):
            pass

        debug = error = info

    keys = [PrivateKey.from_seed(b"profile-%d" % i) for i in range(n_validators)]
    powers = {k.address: 1 for k in keys}
    src = ECDSABackend.static_validators(powers)
    nodes: list = []

    def gossip(message):
        for _, ingress in nodes:
            ingress.submit(message)

    class _T:
        def multicast(self, message):
            gossip(message)

    for k in keys:
        backend = ECDSABackend(k, src)
        _patch_backend(tally, backend)
        verifier = AdaptiveBatchVerifier(src)
        _patch_verifier(tally, verifier)  # covers both routes (host + device)
        core = IBFT(_Null(), backend, _T(), batch_verifier=verifier)
        core.set_base_round_timeout(30.0)
        nodes.append(
            (core, WindowedIngress(BatchingIngress(core.add_messages), tally))
        )

    async def run() -> list:
        # Untimed warmup height: process-wide first-use costs (native-lib
        # registration, codec caches) land here, not in the profile.
        await asyncio.wait_for(
            asyncio.gather(*(core.run_sequence(1) for core, _ in nodes)), 60
        )
        tally.totals.clear()
        tally.counts.clear()
        per_height = []
        for h in range(2, heights + 2):
            t0 = time.perf_counter()
            await asyncio.wait_for(
                asyncio.gather(*(core.run_sequence(h) for core, _ in nodes)), 60
            )
            per_height.append((time.perf_counter() - t0) * 1e3)
        return per_height

    try:
        per_height = asyncio.run(run())
    finally:
        for core, ingress in nodes:
            ingress.close()
            core.messages.close()

    total_ms = sum(per_height)
    components = {
        "sign_ms": tally.ms("sign"),
        "verify_ms": tally.ms("verify"),
        "hash_ms": tally.ms("hash"),
        "window_ms": tally.ms("window"),
    }
    attributed = sum(components.values())
    return {
        "metric": "hotpath_profile",
        "validators": n_validators,
        "heights": heights,
        "height_p50_ms": round(statistics.median(per_height), 3),
        "total_ms": round(total_ms, 3),
        **components,
        "calls": dict(tally.counts),
        "engine_ms": round(max(total_ms - attributed, 0.0), 3),
        "note": (
            "components nest/overlap the wall clock (window runs under the "
            "engine's awaits) — budget, not partition"
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--validators", type=int, default=4)
    ap.add_argument("--heights", type=int, default=7)
    args = ap.parse_args()
    profile = run_profile(args.validators, args.heights)
    print(json.dumps(profile), flush=True)

    from go_ibft_tpu.utils import metrics

    device = {
        "/".join(k): v
        for k, v in getattr(metrics, "_observations", {}).items()
        if "device" in k
    }
    if device:
        print(json.dumps({"metric": "hotpath_device_observations", **device}))


if __name__ == "__main__":
    main()
