"""Compile-budget regression guard: stablehlo line counts of the hot programs.

Trace size IS compile time on XLA:CPU (docs/PERFORMANCE.md): the r04->r05
rounds cut the 8-lane fused certify cold compile 265s -> 55s almost
entirely by shrinking the traced program (mul 811 -> 316 lines,
shear-reshape conv), and this round cut it again (~-31%) by deduplicating
point-op instantiations.  Those wins regress silently — one refactor that
unrolls a scan or forks a new shape instantiation quietly re-adds minutes
of cold compile.  This script LOWERS (never compiles — it stays fast on
any host) the programs that dominate the cold budget, counts their
stablehlo lines, and fails when any grows >10% over the checked-in
snapshot (docs/compile_budget.json).

Usage:
    python scripts/compile_budget.py            # compare vs snapshot (10%)
    python scripts/compile_budget.py --check    # CI ratchet: fail on >2%
    python scripts/compile_budget.py --write    # regenerate the snapshot

``--check`` is the CI gate (ISSUE 9 satellite): the default 10% slack
exists for local iteration, but a program that quietly grows 9% per PR
compounds into minutes of cold compile within a quarter — the ratchet
holds every pinned program within 2% of its snapshot, so growth must be
CONSCIOUS (shrink the program or re-baseline with --write in the same
PR, where review sees the new number).
"""

import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The shard_map mesh entries need virtual devices before the backend
# initializes (same trick as tests/conftest.py).  8 covers the per-dp
# budget sweep (dp = 2/4/8): the sharded program must stay a THIN SHELL
# around the single-chip one at EVERY dp — SPMD propagation or a
# collective regression that re-traces the EC ladder per shard shows up
# as per-dp line growth here first.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

SNAPSHOT = pathlib.Path(__file__).resolve().parent.parent / "docs" / "compile_budget.json"
# Local-iteration slack vs the CI ratchet (--check): trace size is
# cold-compile time, and sub-10% growths compound silently PR over PR.
GROWTH_LIMIT = 0.10
CHECK_GROWTH_LIMIT = 0.02


def _programs() -> dict:
    """Lower each budget-tracked program at its engine-hot shape.

    The shapes live in ``go_ibft_tpu/boot/registry.py`` (ISSUE 16: the
    AOT program store restores the SAME registry at boot, so the budget
    ratchet and the warm-start plane can never pin different programs).
    The why of each pin, kept from the original in-line registry:

    * The multi-chip programs (shard_map meshes at dp = 2/4/8) pin two
      families per dp — ``mesh_quorum_certify`` (the fused dryrun
      program, 8 GLOBAL lanes, keeping the 27,370-line mark comparable)
      and ``mesh_verify_mask`` (the MeshBatchVerifier drain program at 8
      LOCAL lanes per shard, so the per-dp delta isolates the shard_map
      wrapper).  Both must stay thin shells around the single-chip
      program — SPMD propagation or a collective regression that
      re-traces the EC ladder per shard shows up as per-dp growth first.
    * ``bls_aggregate_verify_8v`` (ISSUE 7): the largest trace in the
      repo (~414k stablehlo lines at 8 lanes on jax 0.4.37), the most
      cold-compile-sensitive — a tower-arithmetic refactor that
      re-instantiates the Fp12 ops per call site adds MINUTES of compile.
    * The ISSUE 12 aggregation families — the scanned g2 merge tree at
      the 128-validator bucket (ONE lax.scan over halving levels: bucket
      growth must NOT grow the trace proportionally) and the batched
      multi-pairing Miller stage at 8 lanes.  The final-exp stages are
      deliberately NOT pinned: multi_pairing_check reuses the SAME
      staged jit objects aggregate_verify_commit compiled (identity
      pinned by tests/test_aggregate.py).
    * The ISSUE 14 additions — the keccak digest pack and the G1 merge
      tree — exist so every family the cost ledger attributes has a pin.
    """
    out = {}
    from go_ibft_tpu.boot.registry import program_registry

    for name, build in program_registry().items():
        fn, args = build()
        out[name] = len(fn.lower(*args).as_text().splitlines())
    return out


def _check_sched_reuses_pinned_programs() -> list:
    """ISSUE 8 satellite: the multi-tenant coalesced dispatch
    (go_ibft_tpu/sched/dispatch.py) must run the EXISTING pinned jit
    programs — the very objects verify/batch.py compiled — so process-
    wide coalescing adds NO new program family to this budget (the
    snapshot gains no sched entries by construction).  A refactor that
    re-jits a private copy forks a second compile of the largest
    recover ladder; assert object identity so that fails CI here."""
    from go_ibft_tpu.sched import dispatch as sched_dispatch
    from go_ibft_tpu.verify import batch as vbatch

    failures = []
    if sched_dispatch.DIGEST_KERNEL is not vbatch._digest_kernel:
        failures.append(
            "sched.dispatch.DIGEST_KERNEL is not verify.batch._digest_kernel "
            "— the coalesced plane forked a second digest program"
        )
    if sched_dispatch.RECOVER_KERNEL is not vbatch._recover_kernel:
        failures.append(
            "sched.dispatch.RECOVER_KERNEL is not verify.batch._recover_kernel "
            "— the coalesced plane forked a second recover program"
        )
    print(
        json.dumps(
            {
                "check": "sched_reuses_pinned_programs",
                "status": "FAIL" if failures else "ok",
            }
        )
    )
    return failures


def main() -> int:
    import jax

    growth_limit = (
        CHECK_GROWTH_LIMIT if "--check" in sys.argv else GROWTH_LIMIT
    )
    t0 = time.time()
    measured = _programs()
    measured["_trace_seconds"] = round(time.time() - t0, 1)
    measured["_jax_version"] = jax.__version__

    if "--write" in sys.argv:
        SNAPSHOT.write_text(json.dumps(measured, indent=1) + "\n")
        print(json.dumps({"compile_budget": "snapshot written", **measured}))
        return 0

    identity_failures = _check_sched_reuses_pinned_programs()
    if identity_failures:
        print(
            json.dumps({"compile_budget": "FAIL", "failures": identity_failures})
        )
        return 1

    snapshot = json.loads(SNAPSHOT.read_text())
    if snapshot.get("_jax_version") != jax.__version__:
        # Lowering output is jax-version-sensitive: comparing line counts
        # across versions yields false positives (blocked PRs on an
        # unchanged repo) or false negatives (masked growth).  CI pins the
        # snapshot's jax; a deliberate bump re-baselines with --write.
        print(
            json.dumps(
                {
                    "compile_budget": "FAIL",
                    "failures": [
                        f"snapshot from jax {snapshot.get('_jax_version')} but "
                        f"running jax {jax.__version__}: line counts are not "
                        "comparable across lowering versions — pin jax or "
                        "re-baseline with --write"
                    ],
                }
            )
        )
        return 1
    failures = []
    for name, lines in measured.items():
        if name.startswith("_"):
            continue
        base = snapshot.get(name)
        if base is None:
            failures.append(f"{name}: no snapshot entry (run --write)")
            continue
        growth = (lines - base) / base
        status = "FAIL" if growth > growth_limit else "ok"
        print(
            json.dumps(
                {
                    "program": name,
                    "lines": lines,
                    "snapshot": base,
                    "growth": round(growth, 4),
                    "limit": growth_limit,
                    "status": status,
                }
            )
        )
        if growth > growth_limit:
            failures.append(
                f"{name}: {lines} lines vs snapshot {base} (+{growth:.1%} > "
                f"{growth_limit:.0%}) — trace size is cold-compile time; "
                "shrink the program or consciously re-baseline with --write"
            )
    if failures:
        print(json.dumps({"compile_budget": "FAIL", "failures": failures}))
        return 1
    print(json.dumps({"compile_budget": "ok", "trace_seconds": measured["_trace_seconds"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
